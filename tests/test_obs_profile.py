"""Tests for device-trace attribution (mpi_cuda_process_tpu/obs/profile).

All on synthetic Chrome-trace fixtures — no TPU required.  Pins:

* **parser buckets** — device-lane selection (host lanes never counted),
  comm-vs-compute classification, interval-union math with nested and
  overlapping events;
* **overlap-efficiency arithmetic** — 1 - exposed/total over constructed
  interval layouts (fully hidden, fully exposed, partial, no-comm);
* **honest degradation** — CPU/host-only traces and empty profile dirs
  yield ``attribution: unavailable`` with a reason, never zeros;
* **chunk scoping** — the profiler starts/stops exactly once, at the
  target chunk's boundaries, through the driver's observer hook; and
  the telemetry invariant extends to it: the step/runner jaxpr is
  byte-identical with a profiler attached (zero ops in the scan);
* **CLI wiring** — ``--profile`` composes with ``--telemetry`` (a
  ``profile`` event lands in the log) and refuses ``--tol`` /
  ``--profile-dir`` combinations.
"""

import gzip
import json
import os
import sys

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_process_tpu import (  # noqa: E402
    cli, driver, init_state, make_step, make_stencil,
)
from mpi_cuda_process_tpu.obs import profile, runtime, trace  # noqa: E402


def _meta(pid, name):
    return {"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": name}}


def _ev(pid, name, ts, dur, tid=0):
    return {"ph": "X", "pid": pid, "tid": tid, "name": name,
            "ts": float(ts), "dur": float(dur)}


def _trace(events):
    """A minimal two-process trace: pid 1 = TPU device, pid 9 = host."""
    return [_meta(1, "/device:TPU:0"), _meta(9, "/host:CPU")] + events


# ------------------------------------------------------- parser buckets

def test_device_pid_selection_excludes_host_and_cpu_devices():
    events = [_meta(1, "/device:TPU:0"), _meta(2, "/device:CPU:0"),
              _meta(9, "/host:CPU"), _meta(3, "python")]
    assert profile.device_pids(events) == [1]


def test_comm_classification():
    for name in ("ppermute", "collective-permute.1", "fusion.all-reduce",
                 "send-done.2", "recv.3", "all-to-all"):
        assert profile.is_comm_event(name), name
    for name in ("fusion.17", "add.3", "copy.1", "while", "scan_body"):
        assert not profile.is_comm_event(name), name


def test_attribution_buckets_and_union_math():
    # compute lanes: [0,10) and a NESTED sub-event [2,6) (must not
    # double-count) plus a second lane [8,14) overlapping the first
    events = _trace([
        _ev(1, "fusion.1", 0, 10, tid=0),
        _ev(1, "fusion.1.inner", 2, 4, tid=0),
        _ev(1, "fusion.2", 8, 6, tid=1),
        # comm: [4,9) hidden under compute, [14,18) fully exposed
        _ev(1, "collective-permute.1", 4, 5, tid=2),
        _ev(1, "collective-permute.2", 14, 4, tid=2),
        # host noise that must not be attributed
        _ev(9, "python collective-permute wrapper", 0, 100),
    ])
    att = profile.attribute_events(events)
    assert att["attribution"] == "ok"
    assert att["n_device_events"] == 5
    assert att["compute_us"] == pytest.approx(14.0)   # [0,14)
    assert att["comm_us"] == pytest.approx(9.0)       # [4,9) + [14,18)
    assert att["exposed_comm_us"] == pytest.approx(4.0)
    assert att["device_busy_us"] == pytest.approx(18.0)
    assert att["overlap_efficiency"] == pytest.approx(1 - 4 / 9, abs=1e-4)


def test_overlap_efficiency_extremes():
    fully_hidden = _trace([
        _ev(1, "fusion", 0, 10),
        _ev(1, "ppermute", 2, 3, tid=1),
    ])
    att = profile.attribute_events(fully_hidden)
    assert att["overlap_efficiency"] == pytest.approx(1.0)
    assert att["exposed_comm_us"] == 0.0

    fully_serial = _trace([
        _ev(1, "fusion", 0, 10),
        _ev(1, "ppermute", 10, 5, tid=1),
    ])
    att = profile.attribute_events(fully_serial)
    assert att["overlap_efficiency"] == pytest.approx(0.0)
    assert att["exposed_comm_us"] == pytest.approx(5.0)


def test_no_comm_yields_none_not_perfect_hiding():
    att = profile.attribute_events(_trace([_ev(1, "fusion", 0, 10)]))
    assert att["attribution"] == "ok"
    assert att["overlap_efficiency"] is None
    assert att["comm_us"] == 0.0


def test_host_only_trace_is_unavailable():
    events = [_meta(9, "/host:CPU"), _ev(9, "python stuff", 0, 100)]
    att = profile.attribute_events(events)
    assert att["attribution"] == "unavailable"
    assert "no device lanes" in att["reason"]


def test_device_lane_without_events_is_unavailable():
    att = profile.attribute_events(_trace([]))
    assert att["attribution"] == "unavailable"
    assert "no complete events" in att["reason"]


# -------------------------------------------------------------- file IO

def test_load_trace_events_gz_roundtrip(tmp_path):
    run_dir = tmp_path / "plugins" / "profile" / "2026_08_04"
    run_dir.mkdir(parents=True)
    doc = {"traceEvents": _trace([_ev(1, "fusion", 0, 5)])}
    with gzip.open(run_dir / "host.trace.json.gz", "wt") as fh:
        json.dump(doc, fh)
    events = profile.load_trace_events(str(tmp_path))
    assert any(e.get("name") == "fusion" for e in events)
    att = profile.attribution_record(str(tmp_path), profiled_chunk=1)
    assert att["attribution"] == "ok" and att["profiled_chunk"] == 1


def test_attribution_record_degradations(tmp_path):
    empty = profile.attribution_record(str(tmp_path), profiled_chunk=1)
    assert empty["attribution"] == "unavailable"
    assert "no .trace.json" in empty["reason"]

    never = profile.attribution_record(str(tmp_path), profiled_chunk=None)
    assert never["attribution"] == "unavailable"
    assert "no chunk" in never["reason"]

    err = profile.attribution_record(str(tmp_path), profiled_chunk=0,
                                     error="RuntimeError: boom")
    assert err["attribution"] == "unavailable"
    assert "profiler error" in err["reason"]
    # every degradation formats without raising
    for rec in (empty, never, err):
        assert "unavailable" in profile.format_attribution(rec)


# --------------------------------------------------------- chunk scoping

class _StubProfiler(profile.ChunkProfiler):
    """ChunkProfiler with recorded start/stop calls (no jax.profiler)."""

    def __init__(self, outdir, target_chunk=1):
        self.calls = []
        super().__init__(
            outdir, target_chunk,
            start=lambda d: self.calls.append(("start", d)),
            stop=lambda: self.calls.append(("stop",)))


def test_chunk_profiler_scopes_exactly_the_target_chunk(tmp_path):
    prof = _StubProfiler(str(tmp_path / "prof"), target_chunk=1)
    rec = runtime.RuntimeRecorder(profiler=prof)
    for i in range(4):
        rec.begin_chunk()
        rec.record_chunk(2, 0.01)
    starts = [c for c in prof.calls if c[0] == "start"]
    stops = [c for c in prof.calls if c[0] == "stop"]
    assert len(starts) == 1 and len(stops) == 1
    assert prof.profiled_chunk == 1
    assert rec.chunks[1]["profiled"] is True
    assert all("profiled" not in rec.chunks[i] for i in (0, 2, 3))


def test_chunk_profiler_close_stops_an_open_trace(tmp_path):
    prof = _StubProfiler(str(tmp_path / "prof"), target_chunk=0)
    prof.begin_chunk(0)
    assert prof.active
    prof.close()
    assert not prof.active
    assert prof.calls[-1] == ("stop",)
    prof.close()  # idempotent
    assert prof.calls.count(("stop",)) == 1


def test_profiler_failure_is_recorded_never_raised(tmp_path):
    def boom(_d):
        raise RuntimeError("profiler exploded")

    prof = profile.ChunkProfiler(str(tmp_path), target_chunk=0,
                                 start=boom, stop=lambda: None)
    assert prof.begin_chunk(0) is False
    assert "profiler exploded" in prof.error
    rec = profile.attribution_record(str(tmp_path), profiled_chunk=None,
                                     error=prof.error)
    assert rec["attribution"] == "unavailable"


def test_profiled_run_keeps_step_jaxpr_byte_identical(tmp_path):
    """The telemetry zero-ops invariant extends to --profile: with a
    profiler attached (observer-only chunking, no callback), the traced
    step and runner programs are unchanged."""
    st = make_stencil("heat2d")
    fields = init_state(st, (16, 128), seed=0, kind="pulse")
    step = make_step(st, (16, 128))
    abstract = tuple(jax.ShapeDtypeStruct(f.shape, f.dtype) for f in fields)
    jaxpr_before = str(jax.make_jaxpr(step)(abstract))
    runner_before = str(
        jax.make_jaxpr(driver.make_runner(step, 2, jit=False))(abstract))

    prof = _StubProfiler(str(tmp_path / "prof"), target_chunk=1)
    rec = runtime.RuntimeRecorder(profiler=prof)
    out = driver.run_simulation(st, fields, 8, step_fn=step,
                                log_every=2, observer=rec)
    assert len(rec.chunks) == 4  # observer alone chunks the run
    assert prof.profiled_chunk == 1

    assert str(jax.make_jaxpr(step)(abstract)) == jaxpr_before
    assert str(jax.make_jaxpr(
        driver.make_runner(step, 2, jit=False))(abstract)) == runner_before
    assert out[0].shape == fields[0].shape


# ------------------------------------------------------------ CLI wiring

def test_cli_profile_composes_with_telemetry(tmp_path):
    log = str(tmp_path / "run.jsonl")
    prof_dir = str(tmp_path / "prof")
    cfg = cli.config_from_args([
        "--stencil", "heat2d", "--grid", "32,128", "--iters", "8",
        "--telemetry", log, "--profile", prof_dir])
    cli.run(cfg)
    manifest, events = trace.validate_log(log)
    assert manifest["run"]["profile"] == prof_dir
    profs = [e for e in events if e["kind"] == "profile"]
    assert len(profs) == 1
    p = profs[0]
    # a chunk was scoped even with no --log-every (synthesized boundary)
    assert p["profiled_chunk"] == 1
    chunks = [e for e in events if e["kind"] == "chunk"]
    assert len(chunks) == 2 and chunks[1].get("profiled") is True
    # CPU backend: host-only trace (or none) => explicit degradation,
    # never fabricated zeros
    assert p["attribution"] == "unavailable"
    assert p["reason"]
    non_span = [e for e in events if e["kind"] != "span"]
    assert non_span[-1]["kind"] == "summary"


def test_cli_profile_without_telemetry_still_runs(tmp_path):
    cfg = cli.config_from_args([
        "--stencil", "heat2d", "--grid", "32,128", "--iters", "4",
        "--profile", str(tmp_path / "prof")])
    fields, mcells = cli.run(cfg)
    assert mcells > 0


def test_cli_profile_exclusions():
    with pytest.raises(ValueError, match="while_loop"):
        cli.run(cli.config_from_args([
            "--stencil", "heat2d", "--grid", "32,128", "--iters", "4",
            "--tol", "1e-9", "--profile", "/tmp/x"]))
    with pytest.raises(ValueError, match="nesting"):
        cli.run(cli.config_from_args([
            "--stencil", "heat2d", "--grid", "32,128", "--iters", "4",
            "--profile", "/tmp/x", "--profile-dir", "/tmp/y"]))
