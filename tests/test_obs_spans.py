"""Tests for distributed span tracing + the multi-process roll-up.

Pins the round-16 contracts:

* **span model** — records validate against the obs schema, nest with
  correct parent ids, share one trace_id per session, and carry wall
  start + monotonic-measured duration.
* **cross-process propagation** — ``OBS_TRACE_CONTEXT`` round-trips;
  a session opened under an exported context adopts the trace_id and
  parents its root under the exporter's span; thread-local propagation
  (the engine's in-process path) wins over the environment.
* **supervisor timeline** — attempt/kill/restart/backoff spans land in
  the supervisor log in causal order, the restart span names the next
  attempt's ``resumed_from_step``, and the launcher's ``env_extra``
  exports the attempt span (fake-launcher units; the real-subprocess
  chain is pinned by the tier-1 span smoke).
* **jaxpr invariance** — spans on vs off change NOTHING about the
  jitted step (the telemetry zero-ops pin extended).
* **export** — ``obs_trace_export.py`` folds N logs into one
  schema-valid Chrome trace: hosts/processes as tracks, spans + chunk
  slices + instant markers, trace ids collected.
* **aggregation** — ``obs/aggregate.py`` merges per-process logs
  (distinct ``process_index``) into a per-host table + fleet
  aggregate, served on ``/status.json``.
* **engine request accounting** — submit() opens a request span;
  ``time_to_first_chunk`` lands in handle.status() AND /metrics; the
  engine keeps per-request latency histograms.
* **satellites** — LogTail truncation/rotation reset; obs_top --once
  health exit; ledger best_known gauges on /metrics; CampaignConsole
  complete-lines-only under a racing writer.
"""

import importlib.util
import json
import os
import sys
import threading
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_process_tpu.config import RunConfig  # noqa: E402
from mpi_cuda_process_tpu.obs import aggregate, metrics, serve  # noqa: E402
from mpi_cuda_process_tpu.obs import spans, trace  # noqa: E402


def _load_script(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def trace_export():
    return _load_script("obs_trace_export_t", "scripts/obs_trace_export.py")


@pytest.fixture(scope="module")
def obs_top():
    return _load_script("obs_top_spans_t", "scripts/obs_top.py")


def _manifest(tool="cli", process_index=0, hostname="boxA",
              process_count=1, trace_block=None, **run):
    """A hand-built schema-2 manifest (no jax provenance probe)."""
    m = {
        "schema": trace.SCHEMA_VERSION, "kind": "manifest", "tool": tool,
        "created_at": time.time(), "run": dict(run),
        "provenance": {
            "git_sha": "deadbeef", "jax_version": "0.0-test",
            "backend": "cpu", "device_kind": "cpu", "device_count": 1,
            "framework_version": "test",
            "process_index": process_index,
            "process_count": process_count, "hostname": hostname,
        },
    }
    if trace_block is not None:
        m["trace"] = trace_block
    return trace.validate_manifest(m)


def _read(path):
    return [json.loads(l) for l in open(path) if l.strip()]


# ------------------------------------------------------------ span model

def test_span_records_validate_nest_and_share_trace(tmp_path):
    path = str(tmp_path / "s.jsonl")
    w = trace.TraceWriter(path)
    em = spans.SpanEmitter(w, root_name="cli")
    w.write_manifest(_manifest(trace_block=em.manifest_block()))
    with em.span("outer", step=1) as outer:
        with em.span("inner") as inner:
            assert inner.trace_id == em.trace_id
            assert em.current().span_id == inner.span_id
    em.emit("manual", start=time.time() - 0.5, dur_s=0.5, tag="x")
    em.close()
    em.close()  # idempotent
    w.close()

    manifest, events = trace.validate_log(path)  # every span validates
    recs = {r["name"]: r for r in events if r["kind"] == "span"}
    assert set(recs) == {"outer", "inner", "manual", "cli"}
    assert len({r["trace_id"] for r in recs.values()}) == 1
    assert recs["inner"]["parent_id"] == recs["outer"]["span_id"]
    assert recs["outer"]["parent_id"] == recs["cli"]["span_id"]
    assert recs["manual"]["parent_id"] == recs["cli"]["span_id"]
    assert recs["cli"]["parent_id"] is None  # trace root
    assert manifest["trace"]["trace_id"] == recs["cli"]["trace_id"]
    assert manifest["trace"]["root_span_id"] == recs["cli"]["span_id"]
    for r in recs.values():
        assert r["dur_s"] >= 0 and r["start"] > 0
    assert recs["outer"]["attrs"] == {"step": 1}
    assert recs["manual"]["attrs"] == {"tag": "x"}
    # root emitted LAST (after its children) but starts first
    assert recs["cli"]["start"] <= recs["outer"]["start"]


def test_context_encode_decode_and_resolution(monkeypatch):
    ctx = spans.SpanContext("abc", "def")
    assert spans.SpanContext.decode(ctx.encode()).span_id == "def"
    assert spans.SpanContext.decode("garbage") is None
    assert spans.SpanContext.decode(":x") is None

    monkeypatch.delenv(spans.ENV_VAR, raising=False)
    assert spans.resolve_context() is None
    monkeypatch.setenv(spans.ENV_VAR, "t1:s1")
    assert spans.resolve_context().trace_id == "t1"
    # thread-local (the engine's in-process path) wins over the env
    spans.push_thread_context(spans.SpanContext("t2", "s2"))
    try:
        assert spans.resolve_context().trace_id == "t2"
    finally:
        spans.pop_thread_context()
    assert spans.resolve_context().trace_id == "t1"


def test_session_adopts_env_context_and_disable_gate(
        tmp_path, monkeypatch):
    from mpi_cuda_process_tpu import obs

    monkeypatch.setenv(spans.ENV_VAR, "parenttrace:parentspan")
    path = str(tmp_path / "child.jsonl")
    s = obs.open_session(path, tool="cli", run={}, with_heartbeat=False)
    assert s.spans.trace_id == "parenttrace"
    with s.spans.span("work"):
        pass
    s.close()
    recs = _read(path)
    assert recs[0]["trace"] == {"trace_id": "parenttrace",
                                "root_span_id": s.spans.root_id,
                                "parent_span_id": "parentspan"}
    sp = {r["name"]: r for r in recs if r["kind"] == "span"}
    assert sp["cli"]["parent_id"] == "parentspan"
    assert sp["work"]["trace_id"] == "parenttrace"

    # OBS_SPANS=0: events keep flowing, spans stop
    monkeypatch.setenv("OBS_SPANS", "0")
    path2 = str(tmp_path / "off.jsonl")
    s2 = obs.open_session(path2, tool="cli", run={}, with_heartbeat=False)
    with s2.spans.span("work"):
        pass
    s2.event("chunk", chunk=0, steps=1, wall_s=0.1, ms_per_step=100.0)
    s2.close()
    kinds = [r["kind"] for r in _read(path2)]
    assert "span" not in kinds and "chunk" in kinds


def test_jitted_step_identical_spans_on_vs_off(tmp_path, monkeypatch):
    """Acceptance criterion: the step jaxpr is byte-identical with spans
    on vs off — spans are host-side wall clocks only."""
    import jax

    from mpi_cuda_process_tpu import driver, obs
    from mpi_cuda_process_tpu.ops.stencil import make_stencil
    from mpi_cuda_process_tpu.utils.init import init_state

    st = make_stencil("heat2d")
    step = driver.make_step(st, (16, 128))
    abstract = tuple(jax.ShapeDtypeStruct(f.shape, f.dtype) for f in
                     init_state(st, (16, 128), seed=0, kind="pulse"))
    jaxprs = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("OBS_SPANS", flag)
        s = obs.open_session(str(tmp_path / f"sp{flag}.jsonl"),
                             tool="cli", run={}, with_heartbeat=False)
        # fresh state per leg: the scanned runners donate their buffers
        fields = init_state(st, (16, 128), seed=0, kind="pulse")
        driver.run_simulation(st, fields, 4, step_fn=step, log_every=2,
                              callback=lambda d, fs: None,
                              observer=s.recorder)
        s.close()
        jaxprs[flag] = (str(jax.make_jaxpr(step)(abstract)),
                        str(jax.make_jaxpr(
                            driver.make_runner(step, 4, jit=False))(
                            abstract)))
    assert jaxprs["1"] == jaxprs["0"]
    # spans-on really did emit (the comparison is not vacuous)
    on = _read(str(tmp_path / "sp1.jsonl"))
    assert any(r["kind"] == "span" and r["name"] == "compile"
               for r in on)
    off = _read(str(tmp_path / "sp0.jsonl"))
    assert not any(r["kind"] == "span" for r in off)


# ------------------------------------------------ supervisor timeline

def test_supervise_emits_causal_spans_with_fake_launcher(tmp_path):
    """attempt/kill/restart/backoff spans in causal order, the restart
    naming the resume step, and env_extra exporting the attempt span —
    all without a subprocess (injected launcher/clock/sleep)."""
    from mpi_cuda_process_tpu import obs
    from mpi_cuda_process_tpu.resilience import supervisor as sup

    session = obs.open_session(str(tmp_path / "sup.jsonl"),
                               tool="supervisor", run={},
                               with_heartbeat=False)
    ck = tmp_path / "ck"

    class FakeHandle:
        def __init__(self, rc_sequence):
            self._rcs = rc_sequence

        def poll(self):
            return self._rcs.pop(0) if self._rcs else None

        def kill(self):
            pass

        def wait(self, timeout_s=30.0):
            return 0

    class FakeTail:
        def __init__(self, events):
            self._events = events

        def poll(self):
            ev, self._events = self._events, []
            return ev

    exported = []

    def launcher(attempt, resume):
        exported.append(spans.env_extra(session).get(spans.ENV_VAR))
        if attempt == 0:
            # a WEDGED verdict: the supervisor must kill + restart;
            # fake a surviving npy checkpoint for the resume pointer
            ck.mkdir(parents=True, exist_ok=True)
            (ck / "meta.json").write_text(json.dumps({"step": 30}))
            return FakeHandle([None, None]), [FakeTail(
                [{"kind": "heartbeat", "verdict": "WEDGED"}])]
        return FakeHandle([0]), [FakeTail([])]

    res = sup.supervise(launcher, str(ck), max_restarts=2,
                        backoff_base_s=0.01, stall_timeout_s=60,
                        poll_s=0.0, session=session,
                        sleep=lambda s: None)
    session.close()
    assert res.ok and res.attempts == 2

    recs = _read(str(tmp_path / "sup.jsonl"))
    sp = [r for r in recs if r["kind"] == "span"]
    names = [r["name"] for r in sp]
    for needed in ("attempt", "kill", "restart", "backoff",
                   "supervisor"):
        assert needed in names, names
    assert len({r["trace_id"] for r in sp}) == 1
    attempts = sorted((r for r in sp if r["name"] == "attempt"),
                      key=lambda r: r["start"])
    assert len(attempts) == 2
    restart = next(r for r in sp if r["name"] == "restart")
    assert restart["attrs"]["resumed_from_step"] == 30
    # causal ordering: attempt0 ends <= restart <= attempt1 start
    assert attempts[0]["start"] + attempts[0]["dur_s"] <= \
        restart["start"] + 1e-6
    assert restart["start"] + restart["dur_s"] <= \
        attempts[1]["start"] + 1e-6
    kill = next(r for r in sp if r["name"] == "kill")
    assert kill["parent_id"] == attempts[0]["span_id"]
    backoff = next(r for r in sp if r["name"] == "backoff")
    assert backoff["parent_id"] == restart["span_id"]
    # the launcher ran INSIDE each attempt span: the exported context
    # names the attempt spans, in order
    assert exported == [f"{attempts[0]['trace_id']}:"
                        f"{attempts[0]['span_id']}",
                        f"{attempts[1]['trace_id']}:"
                        f"{attempts[1]['span_id']}"]


# --------------------------------------------------------------- export

def test_trace_export_builds_valid_chrome_trace(tmp_path, trace_export):
    base = str(tmp_path / "run.jsonl")
    suppath = str(tmp_path / "run.supervisor.jsonl")
    childpath = str(tmp_path / "run.attempt0.jsonl")

    w = trace.TraceWriter(suppath)
    em = spans.SpanEmitter(w, root_name="supervisor")
    w.write_manifest(_manifest(tool="supervisor",
                               trace_block=em.manifest_block()))
    w.event("launch", attempt=0, resume=False)
    with em.span("attempt", attempt=0):
        child_ctx = em.current().encode()
    em.close()
    w.close()

    w2 = trace.TraceWriter(childpath)
    em2 = spans.SpanEmitter(w2, context=spans.SpanContext.decode(
        child_ctx), root_name="cli")
    w2.write_manifest(_manifest(trace_block=em2.manifest_block()))
    w2.event("chunk", chunk=0, steps=4, wall_s=0.25, ms_per_step=62.5,
             recompiled=False)
    w2.event("heartbeat", verdict="WEDGED", detail="probe hang")
    em2.close()
    w2.close()

    out = str(tmp_path / "trace.json")
    # the base path never existed: sibling discovery must find both
    assert trace_export.main([base, "-o", out]) == 0
    obj = json.load(open(out))
    assert trace_export.validate_export(obj) == []
    evs = obj["traceEvents"]
    sp = [e for e in evs if e.get("cat") == "span"]
    assert {e["name"] for e in sp} == {"attempt", "supervisor", "cli"}
    assert len({e["args"]["trace_id"] for e in sp}) == 1
    assert obj["otherData"]["trace_ids"] == [em.trace_id]
    # chunk slice synthesized from the event (ts = t - wall_s)
    chunk = next(e for e in evs if e.get("cat") == "chunk")
    assert chunk["ph"] == "X" and chunk["dur"] == pytest.approx(
        0.25e6, rel=1e-3)
    # instant markers: heartbeat verdict + launch
    inames = {e["name"] for e in evs if e["ph"] == "i"}
    assert "heartbeat WEDGED" in inames and "launch attempt 0" in inames
    # both logs on the same host|process track, distinct threads
    assert len({e["pid"] for e in evs}) == 1
    assert len({e["tid"] for e in evs if e["ph"] != "M"}) == 2
    # the child root parents under the exporter's attempt span
    att = next(e for e in sp if e["name"] == "attempt")
    cli_root = next(e for e in sp if e["name"] == "cli")
    assert cli_root["args"]["parent_id"] == att["args"]["span_id"]

    assert trace_export.main([str(tmp_path / "absent.jsonl")]) == 2


# ---------------------------------------------------------- aggregation

def _process_log(tmp_path, idx, gcells_steps=4, wall=0.5,
                 verdict=None, hostname="boxA"):
    path = str(tmp_path / f"proc{idx}.jsonl")
    w = trace.TraceWriter(path)
    em = spans.SpanEmitter(w, root_name="cli")
    w.write_manifest(_manifest(
        process_index=idx, process_count=2, hostname=hostname,
        trace_block=em.manifest_block(),
        stencil="heat2d", grid=[100, 1000], iters=8))
    w.event("chunk", chunk=0, steps=gcells_steps, wall_s=wall,
            ms_per_step=wall * 1e3 / gcells_steps, recompiled=False)
    if verdict:
        w.event("heartbeat", verdict=verdict, detail="t")
    em.close()
    w.close()
    return path


def test_aggregate_merges_processes_into_host_table(tmp_path):
    """Acceptance criterion: >=2 per-process logs (distinct
    process_index) merge into one payload with a per-host table."""
    p0 = _process_log(tmp_path, 0)
    p1 = _process_log(tmp_path, 1, verdict="WEDGED")
    roll = aggregate.aggregate_logs([p0, p1])
    rows = roll["hosts"]
    assert [r["process_index"] for r in rows] == [0, 1]
    assert all(r["hostname"] == "boxA" for r in rows)
    agg = roll["aggregate"]
    assert agg["processes"] == 2 and agg["hosts"] == 1
    assert agg["verdict"] == "WEDGED"  # worst verdict wins
    # fleet throughput = sum of per-process rates (0.1 Mcells * 8/s)
    per = rows[0]["throughput"]["gcells_per_s"]
    assert agg["gcells_per_s"] == pytest.approx(2 * per, rel=1e-6)
    assert len(agg["trace_ids"]) == 2  # independent runs: two traces
    assert rows[0]["time_to_first_chunk_s"] is not None


def test_serve_aggregate_status_json_per_host(tmp_path):
    p0 = _process_log(tmp_path, 0)
    p1 = _process_log(tmp_path, 1)
    server = serve.serve_aggregate([p0, p1], port=0, poll_s=0.05)
    try:
        deadline = time.monotonic() + 10
        status = None
        while time.monotonic() < deadline:
            with urllib.request.urlopen(server.url + "/status.json",
                                        timeout=5) as r:
                status = json.load(r)
            if len(status.get("hosts") or ()) == 2:
                break
            time.sleep(0.05)
        assert status and len(status["hosts"]) == 2
        assert status["aggregate"]["processes"] == 2
        # the merged single-stream payload is still there
        assert "verdict" in status and "throughput" in status
    finally:
        server.close()


# ------------------------------------------------- engine request path

def test_engine_request_span_ttfc_and_latency_histograms(tmp_path):
    from mpi_cuda_process_tpu.engine import SimulationEngine

    eng = SimulationEngine(telemetry_dir=str(tmp_path))
    h = eng.submit(RunConfig(stencil="heat2d", grid=(32, 128), iters=8,
                             log_every=2))
    h.result(timeout=120)
    assert h.timings["queue_wait_s"] >= 0
    assert h.timings["time_to_first_chunk_s"] > 0
    assert h.timings["latency_s"] >= h.timings["time_to_first_chunk_s"]

    st = h.status()
    assert st["request"]["time_to_first_chunk_s"] == \
        h.timings["time_to_first_chunk_s"]
    assert st["request"]["trace_id"] == h.trace_id
    # the log-derived ttfc (manifest -> first chunk) also lands, and
    # the Prometheus rendering of the same stream carries the gauge +
    # the per-request latency histogram
    assert st["time_to_first_chunk_s"] > 0
    rm = metrics.RunMetrics()
    for rec in _read(h.telemetry_path):
        rm.ingest(rec)
    prom = rm.registry.to_prometheus()
    assert "obs_time_to_first_chunk_s" in prom
    assert "obs_span_request_seconds" in prom

    # request span tree in the log: request root + queue_wait/result
    # children, the run's own root parented under the request
    sp = {r["name"]: r for r in _read(h.telemetry_path)
          if r["kind"] == "span"}
    assert sp["request"]["span_id"] == h.request_span_id
    assert sp["request"]["parent_id"] is None
    assert sp["queue_wait"]["parent_id"] == h.request_span_id
    assert sp["cli"]["parent_id"] == h.request_span_id
    assert sp["request"]["attrs"]["ok"] is True

    # engine-level histograms (the scheduler's admission numbers)
    snap = eng.metrics.snapshot()
    assert snap["engine_requests_total"]["value"] == 1
    assert snap["engine_time_to_first_chunk_s"]["count"] == 1
    assert snap["engine_request_latency_s"]["count"] == 1
    assert "engine_time_to_first_chunk_s" in eng.metrics.to_prometheus()
    assert eng.status()["metrics"]["engine_requests_total"]["value"] == 1


def test_engine_failed_request_still_accounted(tmp_path):
    from mpi_cuda_process_tpu.engine import SimulationEngine

    eng = SimulationEngine(telemetry_dir=str(tmp_path))
    h = eng.submit(RunConfig(stencil="heat2d", grid=(32, 128), iters=8,
                             log_every=2, fuse=3))  # 8 % 3 != 0: raises
    with pytest.raises(ValueError):
        h.result(timeout=120)
    assert h.timings["latency_s"] >= 0
    snap = eng.metrics.snapshot()
    assert snap["engine_requests_failed_total"]["value"] == 1
    sp = {r["name"]: r for r in _read(h.telemetry_path)
          if r.get("kind") == "span"}
    assert sp["request"]["attrs"]["ok"] is False


# ------------------------------------------------------------ satellites

def test_logtail_detects_truncation_and_rotation(tmp_path):
    """Satellite: a supervisor restart that reuses a telemetry path
    (TraceWriter opens 'w') must not leave the tail stuck at the old
    offset."""
    path = str(tmp_path / "t.jsonl")
    tail = trace.LogTail(path)
    with open(path, "w") as fh:
        fh.write('{"kind": "a"}\n{"kind": "b"}\n')
    assert [r["kind"] for r in tail.poll()] == ["a", "b"]
    assert tail.poll() == []

    # rotation: the path is rewritten from scratch, shorter than the
    # consumed offset — the tail must reset and read the new content
    with open(path, "w") as fh:
        fh.write('{"kind": "c"}\n')
    assert [r["kind"] for r in tail.poll()] == ["c"]
    assert tail.truncations == 1

    # an append after the reset flows normally
    with open(path, "a") as fh:
        fh.write('{"kind": "d"}\n')
    assert [r["kind"] for r in tail.poll()] == ["d"]

    # truncate-to-empty also resets (pos > size == 0)
    open(path, "w").close()
    assert tail.poll() == []
    with open(path, "a") as fh:
        fh.write('{"kind": "e"}\n')
    assert [r["kind"] for r in tail.poll()] == ["e"]
    assert tail.truncations == 2


def test_obs_top_once_is_a_health_probe(tmp_path, capsys, obs_top):
    """Satellite: --once exits nonzero on WEDGED/STALLED or give-up."""
    def log_with(events):
        path = str(tmp_path / f"h{len(os.listdir(tmp_path))}.jsonl")
        w = trace.TraceWriter(path)
        w.write_manifest(_manifest())
        for kind, payload in events:
            w.event(kind, **payload)
        w.close()
        return path

    healthy = log_with([("chunk", {"chunk": 0, "steps": 2,
                                   "wall_s": 0.1, "ms_per_step": 50.0,
                                   "recompiled": False}),
                        ("summary", {"mcells_per_s": 1.0,
                                     "runtime": {}})])
    assert obs_top.main([healthy, "--once"]) == 0

    wedged = log_with([("heartbeat", {"verdict": "WEDGED",
                                      "detail": "probe hang"})])
    assert obs_top.main([wedged, "--once"]) == 1

    gave_up = log_with([("launch", {"attempt": 0, "resume": False}),
                        ("give_up", {"attempts": 3,
                                     "reason": "wall-clock stall"})])
    assert obs_top.main([gave_up, "--once"]) == 1
    capsys.readouterr()
    # ledger sources have no run health: always 0 (the CI ledger leg)
    path = os.path.join(REPO, "benchmarks", "ledger.jsonl")
    assert obs_top.main([path, "--once"]) == 0
    capsys.readouterr()


def test_ledger_best_known_exported_as_prometheus_gauges(tmp_path):
    """Satellite: the ledger and the live console are one surface."""
    from mpi_cuda_process_tpu.obs import ledger as ledger_lib

    lpath = str(tmp_path / "ledger.jsonl")
    rows = [
        ledger_lib.make_row("heat3d_512_fused4", 107.3, source="t",
                            measured_at=time.time(), backend="tpu"),
        ledger_lib.make_row("wave3d_512", 70.0, source="t",
                            measured_at=time.time(), backend="tpu"),
        # quarantined rows must never surface as gauges
        ledger_lib.make_row("dead_label", 0.0, source="t",
                            measured_at=time.time(), backend="tpu"),
    ]
    ledger_lib.append_rows(rows, lpath)

    console = serve.RunConsole()
    assert console.load_ledger(lpath) == 2
    prom = console.metrics.registry.to_prometheus()
    assert 'obs_ledger_best_known{backend="tpu",' \
           'label="heat3d_512_fused4",unit="Mcells/s"} 107.3' in prom
    assert 'label="wave3d_512"' in prom
    assert "dead_label" not in prom
    # missing ledger: served console degrades to zero baselines
    assert serve.RunConsole().load_ledger(
        str(tmp_path / "absent.jsonl")) == 0


def test_campaign_console_complete_lines_only_under_racing_writer(
        tmp_path):
    """Satellite: the directory rescan racing a writer mid-append must
    hold the complete-lines-only invariant — a torn line is never
    ingested, and it IS ingested once its terminator lands."""
    console = serve.CampaignConsole(str(tmp_path))

    # deterministic torn write: half a record, no newline
    p1 = tmp_path / "a.jsonl"
    manifest_line = json.dumps(_manifest(tool="measure")) + "\n"
    event_line = json.dumps({"schema": trace.SCHEMA_VERSION,
                             "kind": "label", "t": time.time(),
                             "label": "L0", "status": "ok"}) + "\n"
    with open(p1, "w") as fh:
        fh.write(manifest_line)
        fh.write(event_line[:len(event_line) // 2])
        fh.flush()
    console.poll()
    assert console.seq == 1  # the manifest only; the torn tail waits
    assert console.metrics.labels == {}
    with open(p1, "a") as fh:
        fh.write(event_line[len(event_line) // 2:])
    console.poll()
    assert console.seq == 2 and "L0" in console.metrics.labels

    # stress: a writer starting NEW label files (concurrent label
    # starts) while appending records byte-by-byte, racing the rescan
    n_files, per_file = 3, 20
    stop = threading.Event()

    def writer():
        for i in range(n_files):
            path = tmp_path / f"w{i}.jsonl"
            with open(path, "w") as fh:
                fh.write(json.dumps(_manifest(tool="measure")) + "\n")
                for j in range(per_file):
                    line = json.dumps(
                        {"schema": trace.SCHEMA_VERSION, "kind": "label",
                         "t": time.time(), "label": f"w{i}-{j}",
                         "status": "ok"}) + "\n"
                    mid = len(line) // 2
                    fh.write(line[:mid])
                    fh.flush()
                    time.sleep(0.001)
                    fh.write(line[mid:])
                    fh.flush()
        stop.set()

    t = threading.Thread(target=writer)
    t.start()
    while not stop.is_set():
        console.poll()
        time.sleep(0.002)
    t.join()
    console.poll()
    expected = 2 + n_files * (per_file + 1)
    assert console.seq == expected
    # every ingested record arrived whole (no half-line ever parsed):
    # all label events are present and every tail stayed well-formed
    assert sum(1 for lbl in console.metrics.labels
               if lbl.startswith("w")) == n_files * per_file
    assert all(tail.malformed == 0 for _p, tail in console._tails)
