"""Physics-property tests for the diffusion models (SURVEY.md §4.1)."""

import numpy as np

import jax.numpy as jnp

from mpi_cuda_process_tpu import init_state, make_step, make_stencil, run_simulation


def test_hot_walls_reach_uniform_steady_state():
    """MDF's analytic steady state: all-100 with hot Dirichlet walls."""
    st = make_stencil("heat2d", bc=100.0)
    fields = init_state(st, (16, 16), kind="zero")
    assert float(fields[0][0, 0]) == 100.0  # wall
    assert float(fields[0][5, 5]) == 0.0  # interior
    out = run_simulation(st, fields, 3000)
    np.testing.assert_allclose(np.asarray(out[0]), 100.0, atol=1e-2)


def test_maximum_principle():
    """Diffusion never exceeds the initial/boundary extrema."""
    rng = np.random.default_rng(0)
    g = (rng.random((12, 12, 12)) * 100).astype(np.float32)
    st = make_stencil("heat3d")
    lo, hi = float(g.min()), float(g.max())
    out = run_simulation(st, (jnp.asarray(g),), 50)
    a = np.asarray(out[0])
    assert a.min() >= lo - 1e-3 and a.max() <= hi + 1e-3


def test_heat27_smooths_toward_walls():
    st = make_stencil("heat3d27", bc=100.0, alpha=0.1)
    fields = init_state(st, (10, 10, 10), kind="zero")
    out = run_simulation(st, fields, 500)
    a = np.asarray(out[0])
    assert a.min() > 50.0  # well on the way to uniform 100


def test_wave_energy_bounded():
    st = make_stencil("wave3d", c2dt2=0.1)
    fields = init_state(st, (16, 16, 16), kind="pulse")
    out = run_simulation(st, fields, 100)
    a = np.asarray(out[0])
    assert np.isfinite(a).all()
    assert np.abs(a).max() < 10.0  # stable, no blow-up
