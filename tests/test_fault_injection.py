"""Fault injection: kill a running simulation process, resume, bit-match.

SURVEY.md §5.3: the reference has no failure story at all — a dead rank hangs
its peer forever in blocking MPI_Recv (kernel.cu:215).  This framework's
recovery path is checkpoint/restart; this test proves it end-to-end by
SIGKILLing a live run mid-flight (no atexit, no flush — a real crash) and
resuming from whatever checkpoint survived.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = """
import sys; sys.path.insert(0, {repo!r})
import os
os.environ.pop("XLA_FLAGS", None)
import jax; jax.config.update("jax_platforms", "cpu")
from mpi_cuda_process_tpu.cli import main
main([
    "--stencil", "life", "--grid", "64,64", "--iters", "2000", "--seed", "11",
    "--checkpoint-every", "10", "--checkpoint-dir", {ck!r},
    "--log-every", "10",
])
"""


def test_sigkill_then_resume_bitmatch(tmp_path):
    from mpi_cuda_process_tpu.cli import run
    from mpi_cuda_process_tpu.config import RunConfig
    from mpi_cuda_process_tpu.utils import checkpointing

    ck = str(tmp_path / "ck")
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD.format(repo=REPO, ck=ck)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    # wait for a mid-run checkpoint, then crash the process hard
    deadline = time.time() + 120
    step = None
    while time.time() < deadline:
        step = checkpointing.latest_step(ck)
        if step is not None and 10 <= step < 2000:
            break
        if proc.poll() is not None:
            raise AssertionError("child exited before being killed")
        time.sleep(0.2)
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)
    step = checkpointing.latest_step(ck)
    assert step is not None and step < 2000, f"no mid-run checkpoint: {step}"

    # resume to a fixed horizon and compare against an uninterrupted run
    horizon = step + 20
    base = dict(stencil="life", grid=(64, 64), seed=11)
    resumed, _ = run(RunConfig(**base, iters=horizon, resume=True,
                               checkpoint_dir=ck, checkpoint_every=10))
    full, _ = run(RunConfig(**base, iters=horizon))
    np.testing.assert_array_equal(
        np.asarray(resumed[0]), np.asarray(full[0]))
