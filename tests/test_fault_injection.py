"""Fault injection: kill/hang a live run deterministically, resume, bit-match.

SURVEY.md §5.3: the reference has no failure story at all — a dead rank hangs
its peer forever in blocking MPI_Recv (kernel.cu:215).  This framework's
recovery path is checkpoint/restart; this suite proves it end-to-end with
the deterministic fault harness (``resilience/faults.py``): a child process
inherits ``FAULT_INJECT`` and dies/hangs at an exact declared point (no
sleep-and-hope races), then the parent resumes from whatever checkpoint
survived and the result must bit-match an uninterrupted run.

Covered here: SIGKILL at an exact step boundary (npy AND orbax backends),
SIGKILL *during* a checkpoint write (the atomic-rename window — no
truncated checkpoint is ever loadable), plus the original race-based kill
(kept: it is the only test that kills at a point NOT declared in advance).
The supervisor built on these primitives is proven in
``tests/test_supervisor.py``.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = """
import sys; sys.path.insert(0, {repo!r})
import os
os.environ.pop("XLA_FLAGS", None)
import jax; jax.config.update("jax_platforms", "cpu")
from mpi_cuda_process_tpu.cli import main
main({argv!r})
"""

_SIGKILL = -signal.SIGKILL


def _run_child(argv, fault, extra_env=None, timeout=240):
    """Run a CPU CLI child with ``FAULT_INJECT=fault``; return its rc."""
    env = dict(os.environ, FAULT_INJECT=fault, FAULT_ATTEMPT="0")
    env.update(extra_env or {})
    p = subprocess.run(
        [sys.executable, "-c", _CHILD.format(repo=REPO, argv=list(argv))],
        env=env, timeout=timeout,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    return p.returncode


def _bitmatch(resumed_fields, reference_fields):
    for a, b in zip(resumed_fields, reference_fields):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sigkill_then_resume_bitmatch(tmp_path):
    """The original RACE-based kill: no declared fault point, a live run
    SIGKILLed at whatever step it happens to be on.  Kept alongside the
    deterministic suite — it is the only test whose kill point the code
    under test cannot anticipate."""
    from mpi_cuda_process_tpu.cli import run
    from mpi_cuda_process_tpu.config import RunConfig
    from mpi_cuda_process_tpu.utils import checkpointing

    ck = str(tmp_path / "ck")
    argv = ["--stencil", "life", "--grid", "64,64", "--iters", "2000",
            "--seed", "11", "--checkpoint-every", "10",
            "--checkpoint-dir", ck, "--log-every", "10"]
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD.format(repo=REPO, argv=argv)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    # wait for a mid-run checkpoint, then crash the process hard
    deadline = time.time() + 120
    step = None
    while time.time() < deadline:
        step = checkpointing.latest_step(ck)
        if step is not None and 10 <= step < 2000:
            break
        if proc.poll() is not None:
            raise AssertionError("child exited before being killed")
        time.sleep(0.2)
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)
    step = checkpointing.latest_step(ck)
    assert step is not None and step < 2000, f"no mid-run checkpoint: {step}"

    # resume to a fixed horizon and compare against an uninterrupted run
    horizon = step + 20
    base = dict(stencil="life", grid=(64, 64), seed=11)
    resumed, _ = run(RunConfig(**base, iters=horizon, resume=True,
                               checkpoint_dir=ck, checkpoint_every=10))
    full, _ = run(RunConfig(**base, iters=horizon))
    _bitmatch(resumed, full)


def test_fault_sigkill_at_step_resume_bitmatch(tmp_path):
    """Deterministic mid-run death: FAULT_INJECT=exchange:step=40:sigkill
    fires at the step-40 chunk boundary BEFORE that boundary's save, so
    the newest survivor is exactly step 30 — no polling, no race."""
    from mpi_cuda_process_tpu.cli import run
    from mpi_cuda_process_tpu.config import RunConfig
    from mpi_cuda_process_tpu.utils import checkpointing

    ck = str(tmp_path / "ck")
    rc = _run_child(
        ["--stencil", "life", "--grid", "64,64", "--iters", "2000",
         "--seed", "11", "--checkpoint-every", "10",
         "--checkpoint-dir", ck],
        fault="exchange:step=40:sigkill")
    assert rc == _SIGKILL, f"child should die by SIGKILL, rc={rc}"
    assert checkpointing.latest_step(ck) == 30

    base = dict(stencil="life", grid=(64, 64), seed=11)
    resumed, _ = run(RunConfig(**base, iters=60, resume=True,
                               checkpoint_dir=ck, checkpoint_every=10))
    full, _ = run(RunConfig(**base, iters=60))
    _bitmatch(resumed, full)


def test_fault_sigkill_during_checkpoint_write_atomic(tmp_path):
    """SIGKILL in the atomic-rename window: the step-20 payload is fully
    written to the temp dir but never renamed into place.  The rename
    guarantee means the step-10 checkpoint stays the newest LOADABLE
    state — a truncated/unrenamed checkpoint must never be loadable."""
    from mpi_cuda_process_tpu.cli import run
    from mpi_cuda_process_tpu.config import RunConfig
    from mpi_cuda_process_tpu.utils import checkpointing

    ck = str(tmp_path / "ck")
    rc = _run_child(
        ["--stencil", "life", "--grid", "64,64", "--iters", "2000",
         "--seed", "11", "--checkpoint-every", "10",
         "--checkpoint-dir", ck],
        fault="checkpoint:during_write:step=20:sigkill")
    assert rc == _SIGKILL
    # the interrupted write left its temp dir behind (the kill preempted
    # cleanup) but the checkpoint the loader sees is the intact step 10
    assert checkpointing.checkpoint_format(ck) == "npy"
    assert checkpointing.latest_step(ck) == 10
    fields, step, _ = checkpointing.load_any(ck)
    assert step == 10 and all(np.isfinite(f).all() if np.issubdtype(
        f.dtype, np.inexact) else True for f in fields)

    base = dict(stencil="life", grid=(64, 64), seed=11)
    resumed, _ = run(RunConfig(**base, iters=40, resume=True,
                               checkpoint_dir=ck, checkpoint_every=10))
    full, _ = run(RunConfig(**base, iters=40))
    _bitmatch(resumed, full)


def test_fault_sigkill_before_first_checkpoint_write(tmp_path):
    """Death before ANY completed save: nothing loadable may exist (a
    partially-materialized first checkpoint would resume garbage)."""
    from mpi_cuda_process_tpu.utils import checkpointing

    ck = str(tmp_path / "ck")
    rc = _run_child(
        ["--stencil", "life", "--grid", "64,64", "--iters", "2000",
         "--seed", "11", "--checkpoint-every", "10",
         "--checkpoint-dir", ck],
        fault="checkpoint:during_write:sigkill")  # first save, step 10
    assert rc == _SIGKILL
    assert checkpointing.checkpoint_format(ck) is None
    assert checkpointing.latest_step(ck) is None
    with pytest.raises(FileNotFoundError):
        checkpointing.load_any(ck)


def test_fault_sigkill_orbax_resume_bitmatch(tmp_path):
    """The orbax backend gets the same deterministic sigkill-resume-
    bitmatch contract the npy backend has: per-shard checkpoints written
    before the kill restore bit-exactly onto the resumed run."""
    from mpi_cuda_process_tpu.cli import run
    from mpi_cuda_process_tpu.config import RunConfig
    from mpi_cuda_process_tpu.utils import checkpointing

    ck = str(tmp_path / "ck")
    rc = _run_child(
        ["--stencil", "life", "--grid", "64,64", "--iters", "2000",
         "--seed", "11", "--checkpoint-every", "10",
         "--checkpoint-dir", ck, "--checkpoint-backend", "orbax"],
        fault="exchange:step=40:sigkill")
    assert rc == _SIGKILL
    assert checkpointing.checkpoint_format(ck) == "orbax"
    assert checkpointing.latest_step(ck) == 30

    base = dict(stencil="life", grid=(64, 64), seed=11)
    resumed, _ = run(RunConfig(**base, iters=60, resume=True,
                               checkpoint_dir=ck, checkpoint_every=10,
                               checkpoint_backend="orbax"))
    full, _ = run(RunConfig(**base, iters=60))
    _bitmatch(resumed, full)
