"""Tests for the campaign ledger + perf regression gate.

Synthetic manifests only — no TPU required.  Pins:

* **quarantine rules** — 0.0/missing values, ``stale`` replays (flagged
  OR note-marked), noise-floor suspects, errored labels, backend
  mismatches, and WEDGED/STALLED heartbeats all land quarantined with
  a reason, and :func:`best_known` can never surface one as a baseline;
* **backfill idempotence** — the one-shot historical ingest of the
  repo's real BENCH_r0*/results_r0* files appends once and never again;
* **gate verdicts** — IMPROVED/OK/REGRESSED/NO_BASELINE/QUARANTINED
  against a backfilled ledger, nonzero exit on an injected synthetic
  regression, ``--dry`` always 0 (the acceptance criteria);
* **wedged-path routing** — bench.py's stale fallback record enters the
  ledger quarantined (satellite), carrying its heartbeat verdict and
  the ``last_real_measurement`` pointer.
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_process_tpu.obs import heartbeat  # noqa: E402
from mpi_cuda_process_tpu.obs import ledger, trace  # noqa: E402


def _load_script(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_log(tmp_path, rec, name="bench.jsonl"):
    """A schema-valid bench-tool telemetry log with one result event."""
    path = str(tmp_path / name)
    with trace.TraceWriter(path) as w:
        w.write_manifest(trace.build_manifest("bench", {"grid": [16] * 3}))
        w.event("result", **rec)
    return path


# ------------------------------------------------------ quarantine rules

def test_classify_quarantines_every_bad_shape():
    assert ledger.classify(100.0) == ("ok", None)
    for kw, frag in (
        (dict(value=0.0), "zero/missing"),
        (dict(value=None), "zero/missing"),
        (dict(value=100.0, stale=True), "stale"),
        (dict(value=100.0, suspect=True), "suspect"),
        (dict(value=100.0, error="OOM"), "errored"),
        (dict(value=100.0, backend="tpu", expected_backend="cpu"),
         "backend mismatch"),
        (dict(value=100.0, heartbeat="WEDGED"), "WEDGED"),
        (dict(value=100.0, heartbeat="STALLED"), "STALLED"),
    ):
        kw = dict(kw)
        value = kw.pop("value")
        status, reason = ledger.classify(value, **kw)
        assert status == "quarantined", kw
        assert frag in reason, (kw, reason)


def test_best_known_structurally_excludes_quarantined():
    rows = [
        ledger.make_row("lab", 50.0, source="a", backend="tpu",
                        expected_backend="tpu", measured_at=1.0),
        ledger.make_row("lab", 80.0, source="b", backend="tpu",
                        expected_backend="tpu", measured_at=2.0),
        # bigger but stale: must never win
        ledger.make_row("lab", 999.0, source="c", backend="tpu",
                        expected_backend="tpu", stale=True,
                        measured_at=3.0),
        # bigger but 0.0-style wedge on another label
        ledger.make_row("lab2", 0.0, source="d", backend="tpu",
                        expected_backend="tpu", measured_at=4.0),
    ]
    best = ledger.best_known(rows)
    assert set(best) == {"lab|tpu"}
    assert best["lab|tpu"]["value"] == 80.0
    assert best["lab|tpu"]["source"] == "b"  # provenance rides along


def test_cpu_and_tpu_rows_never_share_a_baseline():
    rows = [ledger.make_row("lab", 10.0, source="cpu-run", backend="cpu",
                            expected_backend="cpu"),
            ledger.make_row("lab", 90.0, source="tpu-run", backend="tpu",
                            expected_backend="tpu")]
    best = ledger.best_known(rows)
    assert best["lab|cpu"]["value"] == 10.0
    assert best["lab|tpu"]["value"] == 90.0


def test_bench_note_only_replay_is_quarantined(tmp_path):
    """BENCH_r01's cached replay predates the ``stale`` flag — the note
    prose is the only marker, and it must still quarantine."""
    log = _bench_log(tmp_path, {
        "metric": "heat3d_7pt_256cubed_single_chip_throughput",
        "value": 88859.1, "unit": "Mcells/s", "backend": "tpu",
        "note": "cached tpu-backend result: backend unresponsive this "
                "run"})
    rows = ledger.rows_from_log(log)
    assert len(rows) == 1
    assert rows[0]["status"] == "quarantined"
    assert "stale" in rows[0]["quarantine"]


def test_append_rows_idempotent_and_validating(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    row = ledger.make_row("lab", 10.0, source="s", backend="cpu",
                          expected_backend="cpu", measured_at=1.5)
    assert ledger.append_rows([row], path) == 1
    assert ledger.append_rows([row], path) == 0  # same uid: skipped
    assert len(ledger.read_rows(path)) == 1
    with pytest.raises(ValueError, match="status"):
        ledger.append_rows([dict(row, status="great")], path)
    # a corrupt line is loud, with its line number
    with open(path, "a") as fh:
        fh.write('{"kind": "ledger_row"}\n')
    with pytest.raises(ValueError, match=":2"):
        ledger.read_rows(path)


# ------------------------------------------------------------- backfill

def test_backfill_is_idempotent_and_quarantines_wedged_rounds(tmp_path):
    """The repo's REAL historical files: BENCH_r04/r05 (0.0 stale) and
    every suspect/errored campaign label land quarantined; round-3
    measurements land ok; a second backfill appends nothing."""
    path = str(tmp_path / "ledger.jsonl")
    out = ledger.backfill(repo=REPO, ledger_path=path)
    assert out["appended"] == out["found"] > 0
    again = ledger.backfill(repo=REPO, ledger_path=path)
    assert again["appended"] == 0  # idempotent

    rows = ledger.read_rows(path)
    by_src = {}
    for r in rows:
        by_src.setdefault(r["source"], []).append(r)
    # the replay/wedge scoreboards: r01 (note-marked cached replay), r03
    # (stale flag), r04/r05 (0.0 unmeasured) — all quarantined; r02 was
    # a genuine fresh round-2 measurement and must survive as ok
    for src in ("BENCH_r01.json", "BENCH_r03.json", "BENCH_r04.json",
                "BENCH_r05.json"):
        assert all(r["status"] == "quarantined" for r in by_src[src]), src
    assert all(r["status"] == "ok" for r in by_src["BENCH_r02.json"])
    # the campaign tables carry real measurements that survive as ok
    ok_rows = [r for r in rows if r["status"] == "ok"]
    assert any(r["source"].startswith("results_r0") for r in ok_rows)
    assert all((r["value"] or 0) > 0 for r in ok_rows)
    # and no 0.0 anywhere in the baseline view
    best = ledger.best_known(rows)
    assert best
    assert all(r["status"] == "ok" and r["value"] > 0
               for r in best.values())


# ---------------------------------------------------------- gate verdicts

@pytest.fixture()
def gate_mod():
    return _load_script("perf_gate_t", "scripts/perf_gate.py")


def _seed_baseline(tmp_path, label, value, backend="cpu"):
    path = str(tmp_path / "ledger.jsonl")
    ledger.append_rows([ledger.make_row(
        label, value, source="seeded-baseline", backend=backend,
        expected_backend=backend, measured_at=100.0)], path)
    return path


def test_gate_all_verdicts(tmp_path, gate_mod, capsys):
    # fresh manifest: one ok row (value 100), one quarantined (stale)
    log = _bench_log(tmp_path, {
        "metric": "m_ok", "value": 100.0, "unit": "Mcells/s",
        "backend": "cpu", "value_512cubed": 100.0,
        "suspect_512cubed": True})
    lpath = str(tmp_path / "ledger.jsonl")
    # baseline equal to fresh -> OK; the stale sibling -> QUARANTINED
    _seed_baseline(tmp_path, "m_ok", 100.0)
    assert gate_mod.main([log, "--ledger", lpath]) == 0
    out = capsys.readouterr().out
    assert "OK" in out and "QUARANTINED" in out

    # IMPROVED: baseline far below
    l2 = str(tmp_path / "l2.jsonl")
    ledger.append_rows([ledger.make_row(
        "m_ok", 10.0, source="old", backend="cpu",
        expected_backend="cpu", measured_at=1.0)], l2)
    assert gate_mod.main([log, "--ledger", l2]) == 0
    assert "IMPROVED" in capsys.readouterr().out

    # NO_BASELINE: empty ledger
    l3 = str(tmp_path / "l3.jsonl")
    assert gate_mod.main([log, "--ledger", l3]) == 0
    assert "NO_BASELINE" in capsys.readouterr().out

    # REGRESSED: baseline far above -> nonzero exit; --dry forces 0
    l4 = str(tmp_path / "l4.jsonl")
    ledger.append_rows([ledger.make_row(
        "m_ok", 1000.0, source="good-old-days", backend="cpu",
        expected_backend="cpu", measured_at=1.0)], l4)
    assert gate_mod.main([log, "--ledger", l4]) == 1
    assert "REGRESSED" in capsys.readouterr().out
    assert gate_mod.main([log, "--ledger", l4, "--dry"]) == 0


def test_gate_noise_band_boundaries(tmp_path, gate_mod, capsys):
    log = _bench_log(tmp_path, {"metric": "m", "value": 91.0,
                                "unit": "Mcells/s", "backend": "cpu"})
    lpath = _seed_baseline(tmp_path, "m", 100.0)
    # 91 vs 100 inside a 10% band -> OK; inside 5% -> REGRESSED
    assert gate_mod.main([log, "--ledger", lpath, "--noise", "0.10"]) == 0
    capsys.readouterr()
    assert gate_mod.main([log, "--ledger", lpath, "--noise", "0.05"]) == 1


def test_gate_quarantined_ledger_rows_never_baseline(tmp_path, gate_mod,
                                                     capsys):
    """Acceptance pin: a ledger full of stale/0.0 rows gives
    NO_BASELINE, not a comparison against garbage."""
    log = _bench_log(tmp_path, {"metric": "m", "value": 5.0,
                                "unit": "Mcells/s", "backend": "cpu"})
    lpath = str(tmp_path / "ledger.jsonl")
    ledger.append_rows([
        ledger.make_row("m", 0.0, source="wedge-r04", backend="cpu",
                        expected_backend="cpu", measured_at=1.0),
        ledger.make_row("m", 9999.0, source="stale-replay", stale=True,
                        backend="cpu", expected_backend="cpu",
                        measured_at=2.0),
    ], lpath)
    assert gate_mod.main([log, "--ledger", lpath]) == 0
    out = capsys.readouterr().out
    assert "NO_BASELINE" in out and "REGRESSED=0" in out


def test_gate_update_ledger_and_self_baseline_exclusion(tmp_path,
                                                        gate_mod, capsys):
    log = _bench_log(tmp_path, {"metric": "m", "value": 50.0,
                                "unit": "Mcells/s", "backend": "cpu"})
    lpath = str(tmp_path / "ledger.jsonl")
    # first gate ingests the run; rows from the SAME manifest are never
    # their own baseline on a re-gate
    assert gate_mod.main([log, "--ledger", lpath, "--update-ledger"]) == 0
    assert any(r["label"] == "m" for r in ledger.read_rows(lpath))
    assert gate_mod.main([log, "--ledger", lpath]) == 0
    assert "NO_BASELINE" in capsys.readouterr().out


def test_restart_trail_rides_rows_and_flags_the_gate(tmp_path, gate_mod,
                                                     capsys):
    """Round-13 satellite: a value measured after a supervised restart
    (measure 'label' events with attempts > 1, cli 'resume' events) is
    judged normally — honest — but carries the trail in the row detail
    and is flagged [after-restart] by the gate, never quarantined."""
    # measure log: one label measured on its second attempt
    mlog = str(tmp_path / "measure.jsonl")
    with trace.TraceWriter(mlog) as w:
        w.write_manifest(trace.build_manifest(
            "measure", {"out": "r.json", "builder_rev": 9}))
        w.event("label", label="lab_retry", status="ok",
                mcells_per_s=50.0, compute="jnp", attempts=2)
    rows = ledger.rows_from_log(mlog)
    assert rows[0]["status"] == "ok"
    assert rows[0]["detail"]["attempts"] == 2
    # cli log: a resumed run names its resume point
    clog = str(tmp_path / "cli.jsonl")
    with trace.TraceWriter(clog) as w:
        w.write_manifest(trace.build_manifest(
            "cli", {"stencil": "life", "grid": [64, 64], "resume": True}))
        w.event("resume", resumed_from_step=30)
        w.event("summary", mcells_per_s=12.0)
    crows = ledger.rows_from_log(clog)
    assert crows[0]["detail"]["resumed_from_step"] == 30

    lpath = _seed_baseline(tmp_path, "lab_retry", 50.0)
    assert gate_mod.main([mlog, "--ledger", lpath]) == 0
    out = capsys.readouterr().out
    assert "[after-restart]" in out and "restarted=1" in out
    assert "QUARANTINED" not in out.split("summary:")[0].split(
        "lab_retry")[1].split("\n")[0]


def test_gate_backfill_mode(tmp_path, gate_mod, capsys, monkeypatch):
    monkeypatch.setenv("OBS_LEDGER_PATH", str(tmp_path / "l.jsonl"))
    assert gate_mod.main(["--backfill"]) == 0
    assert "appended" in capsys.readouterr().out
    assert ledger.read_rows(str(tmp_path / "l.jsonl"))


# ------------------------------------------- telemetry ingestion shapes

def test_ingest_cli_and_scaling_logs(tmp_path):
    lpath = str(tmp_path / "ledger.jsonl")
    cli_log = str(tmp_path / "cli.jsonl")
    with trace.TraceWriter(cli_log) as w:
        w.write_manifest(trace.build_manifest(
            "cli", {"stencil": "heat3d", "grid": [64, 64, 128],
                    "mesh": [2, 1, 1], "fuse": 4, "fuse_kind": "stream",
                    "overlap": True, "pipeline": False}))
        w.event("summary", mcells_per_s=123.4)
    assert ledger.ingest_log(cli_log, lpath) == 1
    row = ledger.read_rows(lpath)[0]
    assert row["status"] == "ok" and row["value"] == 123.4
    assert row["label"] == "cli_heat3d_64x64x128_fuse4_stream_mesh2x1x1_overlap"
    assert row["key"]["flags"]["overlap"] is True

    scal_log = str(tmp_path / "scaling.jsonl")
    with trace.TraceWriter(scal_log) as w:
        w.write_manifest(trace.build_manifest("scaling", {"mode": "weak"}))
        w.event("rung", mode="weak", stencil="heat3d", mesh=[2, 1, 1],
                grid=[64, 64, 128], fuse=4, pipeline=True,
                kernel_kind="zslab", mcells_per_s=77.0)
        w.event("skip", mesh=[4, 1, 1], reason="untileable")
        w.event("summary")
    assert ledger.ingest_log(scal_log, lpath) == 1  # skip events ignored
    rows = ledger.read_rows(lpath)
    srow = [r for r in rows if r["label"].startswith("scaling_")][0]
    assert srow["value"] == 77.0 and srow["key"]["kind"] == "zslab"
    assert "pipeline" in srow["label"]


def test_ingest_measure_log_quarantines_errors(tmp_path):
    lpath = str(tmp_path / "ledger.jsonl")
    log = str(tmp_path / "measure.jsonl")
    with trace.TraceWriter(log) as w:
        w.write_manifest(trace.build_manifest(
            "measure", {"builder_rev": 8}))
        w.event("label", label="good", status="ok", compute="fused4",
                mcells_per_s=55.0, error=None)
        w.event("label", label="hung", status="timeout", compute="padfree4",
                mcells_per_s=None,
                error="subprocess timeout (2400s)")
        w.event("summary", labels_run=2)
    assert ledger.ingest_log(log, lpath) == 2
    rows = {r["label"]: r for r in ledger.read_rows(lpath)}
    assert rows["good"]["status"] == "ok"
    assert rows["good"]["key"]["builder_rev"] == 8
    assert rows["hung"]["status"] == "quarantined"
    assert "errored" in rows["hung"]["quarantine"]
    best = ledger.best_known(rows.values())
    assert [r["label"] for r in best.values()] == ["good"]


def test_wedged_log_heartbeat_quarantines_its_rows(tmp_path):
    lpath = str(tmp_path / "ledger.jsonl")
    log = str(tmp_path / "wedged.jsonl")
    with trace.TraceWriter(log) as w:
        w.write_manifest(trace.build_manifest(
            "cli", {"stencil": "heat3d", "grid": [64, 64, 64]}))
        w.event("heartbeat", verdict="WEDGED", detail="tunnel dead")
        w.event("summary", mcells_per_s=42.0)
    ledger.ingest_log(log, lpath)
    row = ledger.read_rows(lpath)[0]
    assert row["status"] == "quarantined"
    assert "WEDGED" in row["quarantine"]
    assert row["heartbeat"] == "WEDGED"


# -------------------------------------------------- bench wedged routing

def test_bench_wedged_path_routes_quarantined_row(tmp_path, monkeypatch):
    """Satellite: the stale fallback record lands in the ledger
    quarantined, with heartbeat verdict + last_real_measurement
    provenance — and can never be a baseline."""
    lpath = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("OBS_LEDGER_PATH", lpath)
    monkeypatch.setenv("OBS_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("BENCH_OBS_PROBE", "1")
    import bench

    monkeypatch.setattr(
        heartbeat, "probe_verdict",
        lambda timeout_s=0: {"verdict": "WEDGED", "detail": "injected"})
    monkeypatch.setattr(bench, "_CACHE", str(tmp_path / "absent.json"))
    stale = bench._stale_fallback_record()
    assert stale["stale"] is True

    rows = ledger.read_rows(lpath)
    assert rows, "wedged path must write a ledger row"
    assert all(r["status"] == "quarantined" for r in rows)
    r = rows[0]
    assert r["heartbeat"] == "WEDGED"
    assert (r["detail"] or {}).get("last_real_measurement")
    assert ledger.best_known(rows) == {}  # never a baseline

    # idempotent on a double-fire (watchdog + main race)
    n_before = len(rows)
    bench._stale_fallback_record()
    assert len(ledger.read_rows(lpath)) == n_before


# ------------------------------------------------- obs_report --ledger

def test_obs_report_ledger_summary_mode(tmp_path, capsys):
    """Satellite: `obs_report.py --ledger PATH` prints the best_known
    table per label x backend with quarantine counts + reasons — the
    campaign state in one command."""
    import time as _time

    report = _load_script("obs_report_ledger_t", "scripts/obs_report.py")
    lpath = str(tmp_path / "ledger.jsonl")
    now = _time.time()
    rows = [
        ledger.make_row("heat3d_256_f32_fused4", 107.0, source="r03",
                        measured_at=now, backend="tpu"),
        ledger.make_row("heat3d_256_f32_fused4", 99.0, source="r02",
                        measured_at=now - 10, backend="tpu"),
        ledger.make_row("heat3d_256_f32_fused4", 0.0, source="r04",
                        measured_at=now - 5, backend="tpu"),
        ledger.make_row("wave3d_512", 70.0, source="r03",
                        measured_at=now, backend="tpu",
                        heartbeat="WEDGED"),
    ]
    ledger.append_rows(rows, lpath)
    assert report.main(["--ledger", lpath]) == 0
    out = capsys.readouterr().out
    assert "2 quarantined" in out and "1 best-known baselines" in out
    assert "heat3d_256_f32_fused4|tpu" in out and "107.0" in out
    # the wedged label has NO baseline row (structurally excluded)
    assert "wave3d_512|tpu" not in out
    assert "quarantine reasons:" in out
    assert "zero/missing value" in out
    assert "heartbeat verdict WEDGED" in out

    # a missing positional without --ledger is a usage error
    with pytest.raises(SystemExit):
        report.main([])
