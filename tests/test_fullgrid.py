"""Whole-grid 2D temporal blocking == k applications of the plain step.

Unlike the windowed 3D fused kernels (few-ULP tap-order tolerance), the
whole-grid kernel must be BIT-EXACT for int Life and tight for floats: the
entire domain is resident, so there is no temporal-validity margin and no
tile-boundary reassociation.  Pallas interpret mode on CPU (SURVEY.md §4.4).
"""

import jax
import jax.numpy as jnp
import pytest

from mpi_cuda_process_tpu import init_state, make_step, make_stencil
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.ops.pallas.fullgrid import make_fullgrid_step


@pytest.mark.parametrize(
    "name,shape,k,kw",
    [
        ("life", (16, 128), 4, {}),            # int32, bit-exact
        ("life", (16, 128), 7, {}),            # no alignment constraint on k
        ("heat2d", (16, 128), 4, {}),
        ("mdf", (16, 128), 4, {}),             # reference-parameter alias
        ("wave2d", (16, 128), 4, {}),          # two-field leapfrog carry
        ("advect2d", (16, 128), 4, {"cx": -0.4, "cy": 0.2}),
        ("grayscott2d", (16, 128), 4, {}),     # both fields coupled
        ("sor2d", (16, 128), 4, {}),           # red-black multi-phase
    ],
)
def test_fullgrid_matches_plain_steps(name, shape, k, kw):
    st = make_stencil(name, **kw)
    fields = init_state(st, shape, seed=7, kind="auto")
    step = jax.jit(make_step(st, shape))
    ref = fields
    for _ in range(k):
        ref = step(ref)
    fused = make_fullgrid_step(st, shape, k, interpret=True)
    assert fused is not None
    out = jax.jit(fused)(fields)
    assert len(out) == len(ref)
    for o, r in zip(out, ref):
        if jnp.issubdtype(o.dtype, jnp.integer):
            assert jnp.array_equal(o, r)
        else:
            assert jnp.allclose(o, r, rtol=0, atol=1e-5), name


def test_fullgrid_in_scan_runner():
    st = make_stencil("life")
    shape = (16, 128)
    f0 = init_state(st, shape, seed=3, kind="random")
    fused = make_fullgrid_step(st, shape, 4, interpret=True)
    out = make_runner(fused, 3)(f0)
    ref = make_runner(make_step(st, shape), 12)(
        init_state(st, shape, seed=3, kind="random"))
    assert jnp.array_equal(out[0], ref[0])


def test_fullgrid_unsupported_returns_none():
    st = make_stencil("heat2d")
    # odd shapes keep the jnp fallback
    assert make_fullgrid_step(st, (15, 128), 4, interpret=True) is None
    assert make_fullgrid_step(st, (16, 100), 4, interpret=True) is None
    # grids beyond the VMEM budget decline
    assert make_fullgrid_step(st, (8192, 8192), 4, interpret=True) is None
    # 3D models belong to ops/pallas/fused.py
    assert make_fullgrid_step(
        make_stencil("heat3d"), (16, 16, 128), 4, interpret=True) is None


# ---------------------------------------------------------------------------
# sharded + whole-local-block composition: the reference's 1-D row split,
# k generations per exchange
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "name,grid,mesh_n,k,kw",
    [
        ("life", (64, 128), 2, 8, {}),          # default tier: bit-exact int
        pytest.param("sor2d", (64, 128), 2, 8, {},
                     marks=pytest.mark.slow),    # 2-phase margin accounting
        pytest.param("wave2d", (64, 128), 2, 8, {},
                     marks=pytest.mark.slow),    # carry field
        pytest.param("grayscott2d", (64, 128), 2, 8, {},
                     marks=pytest.mark.slow),    # both fields exchanged
        pytest.param("heat2d", (64, 128), 4, 8, {},
                     marks=pytest.mark.slow),    # 4-way split
    ],
)
def test_sharded_fullgrid_matches_unsharded(name, grid, mesh_n, k, kw):
    from mpi_cuda_process_tpu import make_mesh, shard_fields
    from mpi_cuda_process_tpu.parallel.stepper import (
        make_sharded_fullgrid_step,
    )

    st = make_stencil(name, **kw)
    fields = init_state(st, grid, seed=5, density=0.3, kind="auto")
    ref = fields
    step = jax.jit(make_step(st, grid))
    for _ in range(k):
        ref = step(ref)
    mesh = make_mesh((mesh_n,))
    fused = make_sharded_fullgrid_step(st, mesh, grid, k, interpret=True)
    assert fused is not None
    got = jax.jit(fused)(shard_fields(fields, mesh, 2))
    for g, r in zip(got, ref):
        if jnp.issubdtype(g.dtype, jnp.integer):
            assert jnp.array_equal(g, r)
        else:
            assert jnp.allclose(g, r, rtol=0, atol=1e-4), name


def test_sharded_fullgrid_unsupported_configs():
    from mpi_cuda_process_tpu import make_mesh
    from mpi_cuda_process_tpu.parallel.stepper import (
        make_sharded_fullgrid_step,
    )

    st = make_stencil("heat2d")
    # sharded lane axis -> None
    mesh_x = make_mesh((1, 2))
    assert make_sharded_fullgrid_step(
        st, mesh_x, (64, 256), 8, interpret=True) is None
    # local rows smaller than the k-step margin (and sublane-unaligned)
    # -> None.  (Ly == m is legal: the slab is the whole neighbor block —
    # verified bit-exact for heat2d 64x128 on an (8,) mesh.)
    mesh_y = make_mesh((4,))
    assert make_sharded_fullgrid_step(
        st, mesh_y, (16, 128), 8, interpret=True) is None
    # 3D stencils belong to make_sharded_fused_step
    assert make_sharded_fullgrid_step(
        make_stencil("heat3d"), make_mesh((2, 1, 1)), (16, 16, 128), 4,
        interpret=True) is None


@pytest.mark.parametrize("name,kw", [
    ("life", {}),                              # wrap is bit-exact
    ("sor2d", {}),                             # parity under wrap
])
def test_fullgrid_periodic_matches_plain(name, kw):
    st = make_stencil(name, **kw)
    grid = (16, 128)
    f0 = init_state(st, grid, seed=11, density=0.35, kind="random",
                    periodic=True)
    step = jax.jit(make_step(st, grid, periodic=True))
    ref = f0
    for _ in range(8):
        ref = step(ref)
    full = make_fullgrid_step(st, grid, 8, interpret=True, periodic=True)
    assert full is not None
    got = jax.jit(full)(f0)
    for g, r in zip(got, ref):
        if jnp.issubdtype(g.dtype, jnp.integer):
            assert jnp.array_equal(g, r)
        else:
            assert jnp.allclose(g, r, rtol=0, atol=1e-4)


@pytest.mark.slow
def test_sharded_fullgrid_periodic_matches_plain():
    from mpi_cuda_process_tpu import make_mesh, shard_fields
    from mpi_cuda_process_tpu.parallel.stepper import (
        make_sharded_temporal_step,
    )

    st = make_stencil("life")
    grid = (64, 128)
    f0 = init_state(st, grid, seed=6, density=0.35, kind="random",
                    periodic=True)
    step = jax.jit(make_step(st, grid, periodic=True))
    ref = f0
    for _ in range(8):
        ref = step(ref)
    mesh = make_mesh((2,))
    fused = make_sharded_temporal_step(st, mesh, grid, 8, interpret=True,
                                       periodic=True)
    assert fused is not None
    got = jax.jit(fused)(shard_fields(f0, mesh, 2))
    assert jnp.array_equal(got[0], ref[0])
