"""Fused temporal-blocking kernel == k applications of the plain step.

The fused kernel (ops/pallas/fused.py) advances k time steps per HBM pass;
its contract is bit-identical guard-frame semantics to ``driver.make_step``
applied k times.  Runs in Pallas interpret mode on CPU (SURVEY.md §4.4).
"""

import jax
import jax.numpy as jnp
import pytest

from mpi_cuda_process_tpu import init_state, make_step, make_stencil
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.ops.pallas.fused import (
    _pick_tiles,
    make_fused_step,
)


@pytest.mark.parametrize(
    "shape,k",
    [
        ((16, 16, 128), 4),
        ((32, 16, 128), 4),
        ((16, 32, 256), 8),
    ],
)
def test_fused_matches_plain_steps(shape, k):
    st = make_stencil("heat3d")
    fields = init_state(st, shape, seed=3, kind="random")
    step = jax.jit(make_step(st, shape))
    ref = fields
    for _ in range(k):
        ref = step(ref)
    fused = make_fused_step(st, shape, k, interpret=True)
    assert fused is not None
    out = jax.jit(fused)(fields)
    # Identical op order per cell => bit-exact, not just close.
    assert jnp.array_equal(out[0], ref[0])


@pytest.mark.parametrize(
    "name,shape,k,kw",
    [
        ("heat3d27", (16, 16, 128), 4, {"alpha": 0.1}),
        ("heat3d4th", (16, 16, 128), 2, {}),   # halo 2: margin 4, 2m=8
        ("wave3d", (16, 16, 128), 4, {}),      # two-field leapfrog carry
        ("grayscott3d", (16, 16, 128), 4, {}),  # both fields halo'd
        ("advect3d", (16, 16, 128), 4, {}),     # asymmetric upwind taps
        ("advect3d", (16, 16, 128), 4,
         {"cx": -0.3, "cy": 0.2, "cz": -0.1}),  # mixed-sign upwinding
        ("sor3d", (16, 16, 128), 4, {}),        # red-black multi-phase:
                                                # margin 2*halo per micro
    ],
)
def test_fused_families_match_plain_steps(name, shape, k, kw):
    st = make_stencil(name, **kw)
    fields = init_state(st, shape, seed=5, kind="pulse")
    step = jax.jit(make_step(st, shape))
    ref = fields
    for _ in range(k):
        ref = step(ref)
    fused = make_fused_step(st, shape, k, interpret=True)
    assert fused is not None
    out = jax.jit(fused)(fields)
    assert len(out) == len(ref)
    for o, r in zip(out, ref):
        # micro-step tap order differs from the jnp update's association
        # order, so a few-ULP tolerance (frame cells still verbatim below)
        assert jnp.allclose(o, r, rtol=0, atol=1e-4), name
    for o, r in zip(out, ref):
        for d in range(3):
            for sl in (slice(0, st.halo), slice(-st.halo, None)):
                idx = [slice(None)] * 3
                idx[d] = sl
                assert jnp.array_equal(o[tuple(idx)], r[tuple(idx)])


def test_fused_in_scan_runner(_k=4, _n=3):
    st = make_stencil("heat3d")
    shape = (16, 16, 128)
    fields = init_state(st, shape, seed=0, kind="pulse")
    fused = make_fused_step(st, shape, _k, interpret=True)
    out = make_runner(fused, _n)(fields)
    ref = make_runner(make_step(st, shape), _k * _n)(
        init_state(st, shape, seed=0, kind="pulse"))
    assert jnp.allclose(out[0], ref[0], atol=1e-5)


def test_fused_frame_stays_pinned():
    st = make_stencil("heat3d")
    shape = (16, 16, 128)
    fields = init_state(st, shape, seed=1, kind="random")
    fused = make_fused_step(st, shape, 4, interpret=True)
    out = jax.jit(fused)(fields)[0]
    u0 = fields[0]
    for d in range(3):
        lo = [slice(None)] * 3
        hi = [slice(None)] * 3
        lo[d] = 0
        hi[d] = -1
        assert jnp.array_equal(out[tuple(lo)], u0[tuple(lo)])
        assert jnp.array_equal(out[tuple(hi)], u0[tuple(hi)])


def test_unsupported_configs_return_none():
    st = make_stencil("heat3d")
    # k with 2k % 8 != 0 (sublane alignment) is rejected
    assert make_fused_step(st, (16, 16, 128), 2, interpret=True) is None
    # shapes not tileable into aligned blocks are rejected
    assert _pick_tiles(10, 16, 128, 4, 4, 1) is None
    # 2D models have no fused kernel
    assert make_fused_step(
        make_stencil("life"), (32, 32), 4, interpret=True) is None


# ---------------------------------------------------------------------------
# sharded + fused composition: k fused steps per width-k*halo exchange
# ---------------------------------------------------------------------------

# heat3d covers the composition in the default tier; the 27-point and
# two-field variants re-compile the heaviest shard_map+interpret programs
# (~30s each on CPU) and ride the slow tier.
@pytest.mark.parametrize(
    "name,grid,mesh_shape,k,kw",
    [
        ("heat3d", (16, 16, 128), (2, 2, 1), 4, {}),
        pytest.param("heat3d27", (16, 16, 128), (2, 1, 1), 4,
                     {"alpha": 0.1}, marks=pytest.mark.slow),
        pytest.param("wave3d", (32, 16, 128), (2, 2, 1), 4, {},
                     marks=pytest.mark.slow),
        pytest.param("grayscott3d", (16, 16, 128), (2, 1, 1), 4, {},
                     marks=pytest.mark.slow),   # both fields exchanged
        pytest.param("advect3d", (16, 16, 128), (2, 1, 1), 4,
                     {"cx": -0.3, "cy": 0.2, "cz": -0.1},
                     marks=pytest.mark.slow),   # asymmetric across shards
        pytest.param("sor3d", (32, 16, 128), (2, 1, 1), 4, {},
                     marks=pytest.mark.slow),   # parity across shards
    ],
)
def test_sharded_fused_matches_unsharded(name, grid, mesh_shape, k, kw):
    from mpi_cuda_process_tpu import make_mesh, shard_fields
    from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

    st = make_stencil(name, **kw)
    fields = init_state(st, grid, seed=9, kind="pulse")
    ref = fields
    step = jax.jit(make_step(st, grid))
    for _ in range(k):
        ref = step(ref)

    mesh = make_mesh(mesh_shape)
    fused = make_sharded_fused_step(st, mesh, grid, k, interpret=True)
    assert fused is not None
    got = jax.jit(fused)(shard_fields(fields, mesh, 3))
    for g, r in zip(got, ref):
        assert jnp.allclose(g, r, rtol=0, atol=1e-4), name


def test_sharded_fused_unsupported_configs():
    from mpi_cuda_process_tpu import make_mesh
    from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

    st = make_stencil("heat3d")
    # sharded lane axis -> None (in-kernel lane rolls need whole rows)
    mesh = make_mesh((1, 1, 2))
    assert make_sharded_fused_step(
        st, mesh, (16, 16, 256), 4, interpret=True) is None
    # local block smaller than the k*halo margin -> None
    mesh2 = make_mesh((4, 1, 1))
    assert make_sharded_fused_step(
        st, mesh2, (16, 16, 128), 8, interpret=True) is None


def test_fused_periodic_matches_plain_steps():
    """Periodic temporal blocking: wrap-pad + no frame pin == plain wrap."""
    st = make_stencil("heat3d")
    shape = (16, 16, 128)
    fields = init_state(st, shape, seed=4, kind="random", periodic=True)
    step = jax.jit(make_step(st, shape, periodic=True))
    ref = fields
    for _ in range(4):
        ref = step(ref)
    fused = make_fused_step(st, shape, 4, interpret=True, periodic=True)
    assert fused is not None
    out = jax.jit(fused)(fields)
    assert jnp.allclose(out[0], ref[0], rtol=0, atol=1e-4)


@pytest.mark.slow
def test_sharded_fused_periodic_matches_plain():
    from mpi_cuda_process_tpu import make_mesh, shard_fields
    from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

    st = make_stencil("heat3d")
    grid = (16, 16, 128)
    fields = init_state(st, grid, seed=4, kind="random", periodic=True)
    step = jax.jit(make_step(st, grid, periodic=True))
    ref = fields
    for _ in range(4):
        ref = step(ref)
    mesh = make_mesh((2, 2, 1))
    fused = make_sharded_fused_step(st, mesh, grid, 4, interpret=True,
                                    periodic=True)
    assert fused is not None
    got = jax.jit(fused)(shard_fields(fields, mesh, 3))
    assert jnp.allclose(got[0], ref[0], rtol=0, atol=1e-4)


# ---------------------------------------------------------------------------
# pad-free (9-block raw-grid) variant: no full-grid pad transient
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "name,shape,k,kw",
    [
        ("heat3d", (16, 16, 128), 4, {}),
        ("heat3d", (32, 16, 128), 8, {}),       # fori_loop depth
        ("heat3d4th", (16, 16, 128), 2, {}),    # halo 2
        ("wave3d", (16, 16, 128), 4, {}),       # two-field carry
        ("grayscott3d", (16, 16, 128), 4, {}),  # both fields halo'd
        ("advect3d", (16, 16, 128), 4,
         {"cx": -0.3, "cy": 0.2, "cz": -0.1}),  # mixed-sign upwinding
        ("sor3d", (16, 16, 128), 4, {}),        # parity from ghost coords
    ],
)
def test_padfree_matches_plain_steps(name, shape, k, kw):
    st = make_stencil(name, **kw)
    fields = init_state(st, shape, seed=7, kind="pulse")
    step = jax.jit(make_step(st, shape))
    ref = fields
    for _ in range(k):
        ref = step(ref)
    fused = make_fused_step(st, shape, k, interpret=True, padfree=True)
    assert fused is not None
    out = jax.jit(fused)(fields)
    assert len(out) == len(ref)
    for o, r in zip(out, ref):
        assert jnp.allclose(o, r, rtol=0, atol=1e-4), name
    # guard frame verbatim (ghost clamp garbage must never leak inward)
    for o, r in zip(out, ref):
        for d in range(3):
            for sl in (slice(0, st.halo), slice(-st.halo, None)):
                idx = [slice(None)] * 3
                idx[d] = sl
                assert jnp.array_equal(o[tuple(idx)], r[tuple(idx)])


def test_padfree_bitexact_vs_padded():
    """Same tap order as the padded fused kernel => bit-exact match."""
    st = make_stencil("heat3d")
    shape = (16, 16, 128)
    fields = init_state(st, shape, seed=11, kind="random")
    padded = make_fused_step(st, shape, 4, interpret=True)
    padfree = make_fused_step(st, shape, 4, interpret=True, padfree=True)
    assert padded is not None and padfree is not None
    a = jax.jit(padded)(fields)
    b = jax.jit(padfree)(fields)
    assert jnp.array_equal(a[0], b[0])


def test_padfree_periodic_matches_plain_steps():
    """Periodic pad-free: wrapped block indices == wrap-pad values."""
    st = make_stencil("heat3d")
    shape = (16, 16, 128)
    fields = init_state(st, shape, seed=4, kind="random", periodic=True)
    step = jax.jit(make_step(st, shape, periodic=True))
    ref = fields
    for _ in range(4):
        ref = step(ref)
    fused = make_fused_step(st, shape, 4, interpret=True, periodic=True,
                            padfree=True)
    assert fused is not None
    out = jax.jit(fused)(fields)
    assert jnp.allclose(out[0], ref[0], rtol=0, atol=1e-4)


def test_padfree_periodic_sor_parity():
    """Red-black coloring stays globally consistent across wrapped tiles."""
    st = make_stencil("sor3d")
    shape = (16, 16, 128)
    fields = init_state(st, shape, seed=6, kind="pulse", periodic=True)
    step = jax.jit(make_step(st, shape, periodic=True))
    ref = fields
    for _ in range(4):
        ref = step(ref)
    fused = make_fused_step(st, shape, 4, interpret=True, periodic=True,
                            padfree=True)
    assert fused is not None
    out = jax.jit(fused)(fields)
    assert jnp.allclose(out[0], ref[0], rtol=0, atol=1e-4)


# ---------------------------------------------------------------------------
# sharded PAD-FREE (z-slab operands, no exchange-padded transient)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "name,grid,nz,k,kw",
    [
        ("heat3d", (32, 16, 128), 2, 4, {}),
        ("wave3d", (32, 16, 128), 2, 4, {}),     # two-field slabs
        # redundant-variant rows ride the slow tier (CI budget):
        pytest.param("heat3d", (64, 16, 128), 4, 4, {},
                     marks=pytest.mark.slow),    # >2 shards: interior+walls
        pytest.param("sor3d", (32, 16, 128), 2, 4, {},
                     marks=pytest.mark.slow),    # parity via origins
        pytest.param("heat3d4th", (32, 16, 128), 2, 2, {},
                     marks=pytest.mark.slow),    # halo 2
    ],
)
def test_zslab_padfree_matches_unsharded(name, grid, nz, k, kw):
    from mpi_cuda_process_tpu import make_mesh, shard_fields
    from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

    st = make_stencil(name, **kw)
    fields = init_state(st, grid, seed=13, kind="pulse")
    ref = fields
    step = jax.jit(make_step(st, grid))
    for _ in range(k):
        ref = step(ref)
    mesh = make_mesh((nz, 1, 1))
    fused = make_sharded_fused_step(st, mesh, grid, k, interpret=True,
                                    padfree=True)
    assert fused is not None
    got = jax.jit(fused)(shard_fields(fields, mesh, 3))
    for g, r in zip(got, ref):
        assert jnp.allclose(g, r, rtol=0, atol=1e-4), name


def test_zslab_padfree_periodic_matches_unsharded():
    from mpi_cuda_process_tpu import make_mesh, shard_fields
    from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

    st = make_stencil("heat3d")
    grid = (32, 16, 128)
    fields = init_state(st, grid, seed=8, kind="random", periodic=True)
    ref = fields
    step = jax.jit(make_step(st, grid, periodic=True))
    for _ in range(4):
        ref = step(ref)
    mesh = make_mesh((2, 1, 1))
    fused = make_sharded_fused_step(st, mesh, grid, 4, interpret=True,
                                    padfree=True, periodic=True)
    assert fused is not None
    got = jax.jit(fused)(shard_fields(fields, mesh, 3))
    assert jnp.allclose(got[0], ref[0], rtol=0, atol=1e-4)


def test_padfree_y_sharded_mesh_takes_two_axis_kernel():
    from mpi_cuda_process_tpu import make_mesh
    from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

    st = make_stencil("heat3d")
    # y sharded: padfree=True now builds the 2-AXIS slab-operand kernel
    # (y slabs + corner operands) instead of silently falling back to
    # the exchange-padded kernel (the pre-round-7 behavior; equivalence
    # is pinned by tests/test_twoaxis_padfree.py)
    mesh = make_mesh((2, 2, 1))
    step = make_sharded_fused_step(st, mesh, (32, 32, 128), 4,
                                   interpret=True, padfree=True)
    assert step is not None
    assert getattr(step, "_padfree_kind", None) == "yzslab"


# ---------------------------------------------------------------------------
# wide-X z-slab kernel (x windowed at lane-tile granularity)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "name,grid,nz,k,kw",
    [
        ("heat3d", (32, 16, 256), 2, 4, {}),     # bx=128 < X=256: 2 x-tiles
        # the wave row is slow tier (round-8 budget trim): its 90-operand
        # build is per-field replication of the heat3d row's 45-operand
        # machinery (same specs, same selects), and two-field wide-X
        # coverage stays in the default tier via the streaming x-window
        # wave test (test_streamfused::test_xwindowed_wave_two_fields)
        pytest.param("wave3d", (32, 16, 256), 2, 4, {},
                     marks=pytest.mark.slow),    # two-field, 90 operands
        # sor margin is 8 (halo x 2 phases x k=4): tiles must be
        # multiples of 16 — (8,8,128) correctly DECLINES now (see
        # test_xwin_rejects_invalid_explicit_tiles)
        pytest.param("sor3d", (32, 32, 256), 2, 4, {},
                     marks=pytest.mark.slow),    # parity incl. x offsets
    ],
)
def test_xwin_zslab_matches_unsharded(name, grid, nz, k, kw):
    from mpi_cuda_process_tpu import make_mesh, shard_fields
    from mpi_cuda_process_tpu.ops.pallas import fused as F
    from mpi_cuda_process_tpu.parallel import stepper as S

    st = make_stencil(name, **kw)
    # tiles must be multiples of 2*margin (margin doubles for the
    # red-black 2-phase micro)
    g2 = 2 * k * F._halo_per_micro(st)
    tiles = (g2, g2, 128)
    fields = init_state(st, grid, seed=21, kind="pulse")
    ref = fields
    step = jax.jit(make_step(st, grid))
    for _ in range(k):
        ref = step(ref)
    mesh = make_mesh((nz, 1, 1))
    local = (grid[0] // nz, grid[1], grid[2])
    axis_names, counts = S._resolve_mesh_axes(3, mesh)
    fused = S._make_zslab_padfree_step(
        st, mesh, grid, local, axis_names, counts, k,
        lambda *a, **kw2: F.build_zslab_xwin_call(
            *a, tiles=tiles, **kw2),
        (27, 9), True, False)
    assert fused is not None
    got = jax.jit(fused)(shard_fields(fields, mesh, 3))
    for g, r in zip(got, ref):
        assert jnp.allclose(g, r, rtol=0, atol=1e-4), name


def test_xwin_zslab_periodic_matches_unsharded():
    from mpi_cuda_process_tpu import make_mesh, shard_fields
    from mpi_cuda_process_tpu.ops.pallas import fused as F
    from mpi_cuda_process_tpu.parallel import stepper as S

    st = make_stencil("heat3d")
    grid = (32, 16, 256)
    fields = init_state(st, grid, seed=22, kind="random", periodic=True)
    ref = fields
    step = jax.jit(make_step(st, grid, periodic=True))
    for _ in range(4):
        ref = step(ref)
    mesh = make_mesh((2, 1, 1))
    axis_names, counts = S._resolve_mesh_axes(3, mesh)
    fused = S._make_zslab_padfree_step(
        st, mesh, grid, (16, 16, 256), axis_names, counts, 4,
        lambda *a, **kw2: F.build_zslab_xwin_call(
            *a, tiles=(8, 8, 128), **kw2),
        (27, 9), True, True)
    assert fused is not None
    got = jax.jit(fused)(shard_fields(fields, mesh, 3))
    assert jnp.allclose(got[0], ref[0], rtol=0, atol=1e-4)


def test_xwin_unlocks_wave_at_wide_x():
    """The config-5 gap: wave3d at 4096 lanes is untileable for the
    whole-row z-slab kernel but TILEABLE for the wide-X variant — and the
    auto pad-free path reaches it through the builder chain."""
    from mpi_cuda_process_tpu.ops.pallas.fused import (
        build_zslab_padfree_call,
        build_zslab_xwin_call,
    )

    st = make_stencil("wave3d")
    local, gshape = (64, 4096, 4096), (4096, 4096, 4096)
    assert build_zslab_padfree_call(st, local, gshape, 4,
                                    interpret=True) is None
    built = build_zslab_xwin_call(st, local, gshape, 4, interpret=True)
    assert built is not None  # picks VMEM-feasible (bz, by, bx)


def test_xwin_rejects_invalid_explicit_tiles():
    """Explicit tiles bypass the auto picker but NOT the structural
    gates: a bz that is not a multiple of 2*margin degenerated
    _tail_index_fns into silently-wrong geometry (the sor3d wide-X bug
    this test pins)."""
    from mpi_cuda_process_tpu.ops.pallas.fused import (
        build_zslab_padfree_call,
        build_zslab_xwin_call,
    )

    st = make_stencil("sor3d")  # margin 8 at k=4 (2 phases)
    local, gshape = (16, 16, 256), (32, 16, 256)
    assert build_zslab_xwin_call(st, local, gshape, 4, tiles=(8, 8, 128),
                                 interpret=True) is None
    assert build_zslab_padfree_call(st, local, gshape, 4, tiles=(8, 8),
                                    interpret=True) is None
