"""Fused temporal-blocking kernel == k applications of the plain step.

The fused kernel (ops/pallas/fused.py) advances k time steps per HBM pass;
its contract is bit-identical guard-frame semantics to ``driver.make_step``
applied k times.  Runs in Pallas interpret mode on CPU (SURVEY.md §4.4).
"""

import jax
import jax.numpy as jnp
import pytest

from mpi_cuda_process_tpu import init_state, make_step, make_stencil
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.ops.pallas.fused import (
    _pick_tiles,
    make_fused_step,
)


@pytest.mark.parametrize(
    "shape,k",
    [
        ((16, 16, 128), 4),
        ((32, 16, 128), 4),
        ((16, 32, 256), 8),
    ],
)
def test_fused_matches_plain_steps(shape, k):
    st = make_stencil("heat3d")
    fields = init_state(st, shape, seed=3, kind="random")
    step = jax.jit(make_step(st, shape))
    ref = fields
    for _ in range(k):
        ref = step(ref)
    fused = make_fused_step(st, shape, k, interpret=True)
    assert fused is not None
    out = jax.jit(fused)(fields)
    # Identical op order per cell => bit-exact, not just close.
    assert jnp.array_equal(out[0], ref[0])


def test_fused_in_scan_runner(_k=4, _n=3):
    st = make_stencil("heat3d")
    shape = (16, 16, 128)
    fields = init_state(st, shape, seed=0, kind="pulse")
    fused = make_fused_step(st, shape, _k, interpret=True)
    out = make_runner(fused, _n)(fields)
    ref = make_runner(make_step(st, shape), _k * _n)(
        init_state(st, shape, seed=0, kind="pulse"))
    assert jnp.allclose(out[0], ref[0], atol=1e-5)


def test_fused_frame_stays_pinned():
    st = make_stencil("heat3d")
    shape = (16, 16, 128)
    fields = init_state(st, shape, seed=1, kind="random")
    fused = make_fused_step(st, shape, 4, interpret=True)
    out = jax.jit(fused)(fields)[0]
    u0 = fields[0]
    for d in range(3):
        lo = [slice(None)] * 3
        hi = [slice(None)] * 3
        lo[d] = 0
        hi[d] = -1
        assert jnp.array_equal(out[tuple(lo)], u0[tuple(lo)])
        assert jnp.array_equal(out[tuple(hi)], u0[tuple(hi)])


def test_unsupported_configs_return_none():
    st = make_stencil("heat3d")
    # k with 2k % 8 != 0 (sublane alignment) is rejected
    assert make_fused_step(st, (16, 16, 128), 2, interpret=True) is None
    # shapes not tileable into aligned blocks are rejected
    assert _pick_tiles(10, 16, 128, 4, 4) is None
    # only the flagship 7-point model has a fused kernel so far
    assert make_fused_step(
        make_stencil("life"), (32, 32), 4, interpret=True) is None
