#!/usr/bin/env python
"""Collection-count guard: pytest.ini's tier counts must match reality.

pytest.ini shipped stale tier counts twice (round-5 advisor low: the
comments claimed 261 default / 44 slow while the tree collected 276/48 —
updated in the same commit that re-staled them).  The drift class is
"numbers in a comment nobody executes", so this script executes them:
it parses the machine-readable ``tier-counts:`` line in pytest.ini, runs
``pytest --collect-only`` for the default and slow tiers, and exits
nonzero with the fix-it text when they disagree.  Invoked by
``scripts/tier1.sh`` after the test run, so the gate a builder actually
runs also checks the claim.

The same line pins the MULTICHIP-DRYRUN leg count (``dryrun-legs=K``,
round 8): each leg of ``__graft_entry__._dryrun_impl`` is marked by an
explicit ``_leg("name")`` call, counted statically here — a new leg (or
a silently dropped one) fails the gate until pytest.ini moves with it,
exactly the tier-count discipline applied to the driver-visible dryrun.

Counts are environment-sensitive only through optional test deps
(tests/test_properties.py importorskips ``hypothesis``: with it
installed the default tier collects more tests).  The committed numbers
describe the CI container; if your box differs, install/remove the
optional dep rather than editing the counts.
"""

import os
import re
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _declared():
    with open(os.path.join(_REPO, "pytest.ini")) as fh:
        ini = fh.read()
    m = re.search(r"tier-counts:\s*default=(\d+)\s+slow=(\d+)"
                  r"\s+dryrun-legs=(\d+)", ini)
    if not m:
        print("check_tier_counts: no 'tier-counts: default=N slow=M "
              "dryrun-legs=K' line in pytest.ini — add one so the guard "
              "can check it", file=sys.stderr)
        sys.exit(2)
    return int(m.group(1)), int(m.group(2)), int(m.group(3))


def _dryrun_legs():
    """Static count of the ``_leg("...")`` markers in __graft_entry__.py
    (line-anchored so the explanatory comment above the helper never
    counts)."""
    with open(os.path.join(_REPO, "__graft_entry__.py")) as fh:
        src = fh.read()
    return len(re.findall(r'^\s*_leg\("', src, flags=re.MULTILINE))


def _collected(extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q", "--collect-only",
         "-p", "no:cacheprovider"] + extra,
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=300)
    tail = (proc.stdout + proc.stderr)
    m = re.search(r"(\d+)(?:/\d+)? tests? collected", tail)
    if not m:
        print(f"check_tier_counts: could not parse collection output for "
              f"{extra or 'default tier'}:\n{tail[-2000:]}",
              file=sys.stderr)
        sys.exit(2)
    return int(m.group(1))


def main():
    want_default, want_slow, want_legs = _declared()
    got_default = _collected([])            # addopts: not slow and not tpu
    got_slow = _collected(["-m", "slow"])
    got_legs = _dryrun_legs()
    ok = True
    for tier, want, got in (("default", want_default, got_default),
                            ("slow", want_slow, got_slow)):
        if want != got:
            ok = False
            print(f"check_tier_counts: pytest.ini claims {want} {tier}-tier "
                  f"tests but the tree collects {got} — update the "
                  f"'tier-counts:' line in pytest.ini", file=sys.stderr)
    if want_legs != got_legs:
        ok = False
        print(f"check_tier_counts: pytest.ini claims {want_legs} "
              f"multichip-dryrun legs but __graft_entry__.py marks "
              f"{got_legs} with _leg(...) — update the 'dryrun-legs=' "
              f"value (and mark every new leg)", file=sys.stderr)
    if ok:
        print(f"check_tier_counts: ok (default={got_default}, "
              f"slow={got_slow}, dryrun-legs={got_legs})")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
