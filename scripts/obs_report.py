#!/usr/bin/env python
"""Render a telemetry event log into a per-phase attribution report.

Reads a JSONL trace written by the obs/ layer (``cli --telemetry``,
bench.py, benchmarks/measure.py, benchmarks/scaling.py — one shared
manifest schema) and prints:

* the manifest (what ran, where, from which code);
* the static cost model next to the measurement — a per-phase table
  attributing the step budget to interior HBM traffic, the ppermute
  exchange, and the boundary shells, with the roofline's ``overlapped``
  vs ``serial`` predictions bracketing the measured steady-state
  ms/step (the measured number landing between them IS the overlap win,
  quantified — the attribution discipline of arXiv:2108.11076);
* runtime stats (compile vs steady chunks, recompiles, memory peaks),
  heartbeat verdicts, benchmark label/rung records, and how the run
  ended.

``--check`` validates the log against the shared schema and exits
nonzero on any invalid record — the mode ``scripts/tier1.sh`` runs, so
a tool drifting off-schema fails the gate.  A pallas-retry sibling
(``PATH.retry.jsonl``, written by cli.run's auto-retry) is validated
against the same schema when present.

A ``tool="supervisor"`` log has no chunk events (its children's logs
carry those), so instead of an empty attribution table it renders the
launch/restart/give-up trail with ``resumed_from_step``.  ``--ledger``
is the campaign-state mode: the ``best_known`` table per label x
backend plus quarantine counts and reasons, straight from
``benchmarks/ledger.jsonl`` (or a path you pass).

Group-mode logs (``--groups``, PR 18/19) get per-group blocks:
``policy_group`` clause decisions, per-group chunk rates with the
coupled ready-horizon ms/step, ``migrate`` events, and group-named
health verdicts.  ``anomaly`` events (the ``--anomaly`` run doctor)
render as a findings table — their presence means verdict DEGRADED.

When PATH is a flight-recorder bundle (``*.bundle.json``, written by
obs/flightrec.py on a terminal verdict or by ``scripts/obs_bundle.py``)
it is rendered as a self-contained post-mortem — manifest, event ring,
anomaly findings, open spans, ledger baseline, tunnel verdict — with
no need for the original telemetry dir.  ``--check`` on a bundle runs
the bundle's own self-validation instead of the log schema walk.

Safe on a wedged box: the CPU backend is forced before any jax use and
nothing here touches a device.

Usage:  python scripts/obs_report.py PATH [--check]
        python scripts/obs_report.py --ledger [PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from cpuforce import force_cpu  # noqa: E402

force_cpu()  # before the package (and hence any jax backend) loads

from mpi_cuda_process_tpu.obs import trace as obs_trace  # noqa: E402


def _fmt_bytes(b) -> str:
    if b is None:
        return "-"
    if b >= 2**30:
        return f"{b / 2**30:.2f} GiB"
    if b >= 2**20:
        return f"{b / 2**20:.2f} MiB"
    return f"{b} B"


def _table(rows, header):
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    out = ["  ".join(str(h).ljust(w) for h, w in zip(header, widths))]
    out.append("  ".join("-" * w for w in widths))
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


def _manifest_block(m) -> str:
    p = m["provenance"]
    run = m.get("run", {})
    keys = [k for k in ("stencil", "grid", "mesh", "iters", "fuse",
                        "fuse_kind", "overlap", "pipeline", "dtype",
                        "mode", "out", "only", "profile") if run.get(k)]
    lines = [
        f"manifest  tool={m['tool']}  schema={m['schema']}",
        f"  backend={p['backend']} ({p['device_count']}x "
        f"{p['device_kind']})  jax={p['jax_version']}",
        f"  git={p['git_sha'][:12]}  builder_rev={p.get('builder_rev')}  "
        f"framework={p['framework_version']}",
    ]
    if keys:
        lines.append("  run: " + "  ".join(f"{k}={run[k]}" for k in keys))
    return "\n".join(lines)


def _attribution_block(cost, summary) -> str:
    roof = cost.get("roofline", {})
    comm = cost.get("comm")
    t_hbm = roof.get("predicted_ms_per_step_hbm")
    t_ici = roof.get("predicted_ms_per_step_exchange", 0.0)
    measured = None
    if summary:
        steady = (summary.get("runtime") or {}).get("steady") or {}
        measured = steady.get("ms_per_step_p50")

    rows = [["interior (HBM min traffic)",
             f"{t_hbm:.4f}" if t_hbm is not None else "-",
             _fmt_bytes(cost.get("hbm_bytes_per_step_per_device")),
             "(not separable)"]]
    if comm:
        if comm.get("exchange") == "rdma":
            # in-kernel remote-DMA exchange: attribute the ICI traffic
            # by its remote-DMA chunk count (zero ppermute by gate)
            what = (f"exchange ({comm.get('rdma_dma_per_pass')} "
                    f"rdma-dma/pass, width {comm.get('width_m')})")
        else:
            what = (f"exchange ({comm['ppermute_rounds_per_pass']} "
                    f"ppermute/pass, width {comm.get('width_m')})")
        rows.append([
            what,
            f"{t_ici:.4f}",
            _fmt_bytes(int(comm["ici_bytes_per_step"])) + "/step",
            "(not separable)"])
        # boundary shells: cells within 2m of a sharded wall, re-read/
        # re-spliced by the overlap path — bandwidth-priced like interior
        m2 = 2 * (comm.get("width_m") or 0)
        local = cost.get("local_shape") or []
        counts = comm.get("sharded_counts") or []
        if local and counts and t_hbm:
            inner = 1.0
            for d, (ext, cnt) in enumerate(zip(local, counts)):
                if cnt > 1 and ext > m2:
                    inner *= (ext - m2) / ext
            shell_frac = 1.0 - inner
            rows.append([
                "shell (re-splice band, "
                f"{shell_frac * 100:.1f}% of cells)",
                f"{t_hbm * shell_frac:.4f}", "-", "(not separable)"])
    total_over = roof.get("predicted_mcells_per_s_overlapped")
    total_serial = roof.get("predicted_mcells_per_s_serial")
    rows.append(["TOTAL overlapped (exchange hidden)",
                 f"{max(t_hbm or 0, t_ici or 0):.4f}",
                 f"{total_over} Mcells/s",
                 f"{measured:.4f}" if measured is not None else "-"])
    if comm:
        rows.append(["TOTAL serial (exchange on critical path)",
                     f"{(t_hbm or 0) + (t_ici or 0):.4f}",
                     f"{total_serial} Mcells/s", ""])
    return "attribution (predicted vs measured)\n" + _table(
        rows, ["phase", "pred ms/step", "volume", "measured ms/step"])


def _profile_block(prof, cost) -> str:
    """Predicted-vs-measured hiding in one block (the --profile event).

    The roofline's ``overlapped`` prediction assumes the exchange fully
    hidden (efficiency 1.0) and ``serial`` fully exposed (0.0); the
    device trace says where the run actually landed.
    """
    head = "device-trace attribution"
    chunk = prof.get("profiled_chunk")
    if prof.get("attribution") != "ok":
        return (f"{head}: unavailable — "
                f"{prof.get('reason') or 'no reason recorded'}"
                f"  (dir {prof.get('profile_dir')})")
    lines = [f"{head} (profiled chunk {chunk}):"]
    lines.append(
        f"  device busy {prof['device_busy_us'] / 1e3:.3f} ms = "
        f"compute {prof['compute_us'] / 1e3:.3f} ms"
        f" + exchange {prof['comm_us'] / 1e3:.3f} ms"
        f" (exposed {prof['exposed_comm_us'] / 1e3:.3f} ms)"
        f"  [{prof['n_device_events']} device events]")
    eff = prof.get("overlap_efficiency")
    roof = (cost or {}).get("roofline") or {}
    if eff is None:
        lines.append("  no exchange ops in the trace (unsharded run): "
                     "nothing to hide")
    else:
        pred = (f"roofline brackets: overlapped "
                f"{roof.get('predicted_mcells_per_s_overlapped')} vs "
                f"serial {roof.get('predicted_mcells_per_s_serial')} "
                f"Mcells/s" if roof else "no costmodel event to "
                                         "compare against")
        lines.append(f"  measured overlap efficiency {eff:.1%} "
                     f"(1.0 = exchange fully hidden) — {pred}")
    return "\n".join(lines)


def _runtime_block(summary) -> str:
    rt = summary.get("runtime") or {}
    lines = [f"runtime  chunks={rt.get('n_chunks')}  "
             f"steps={rt.get('steps')}  recompiles={rt.get('recompiles')}"]
    if "first_chunk_s" in rt:
        lines.append(f"  compile+first chunk: {rt['first_chunk_s']:.3f}s "
                     f"({rt['first_chunk_ms_per_step']:.4f} ms/step)")
    steady = rt.get("steady")
    if steady:
        lines.append(
            f"  steady ({steady['chunks']} chunks): "
            f"best {steady['ms_per_step_best']:.4f}  "
            f"p50 {steady['ms_per_step_p50']:.4f}  "
            f"p90 {steady['ms_per_step_p90']:.4f} ms/step")
    if "memory_peak_bytes" in rt:
        lines.append(f"  device memory peak: "
                     f"{_fmt_bytes(rt['memory_peak_bytes'])}")
    for k in ("mcells_per_s", "steps", "wall_s", "converged", "residual",
              "labels_run", "note"):
        if k in summary:
            lines.append(f"  {k}: {summary[k]}")
    hb = summary.get("heartbeat")
    if hb:
        lines.append(f"  heartbeat at exit: {hb.get('verdict')}")
    return "\n".join(lines)


def _supervisor_trail_block(events) -> str:
    """The launch/restart/give-up trail of a ``tool="supervisor"`` log.

    A supervisor log has no chunk or costmodel events (the CHILD's logs
    carry those), so the attribution table used to render empty and
    misleading; the trail — which attempt launched when, why each was
    killed, where each resume picked up — is the story this log
    actually tells.
    """
    rows = []
    for e in events:
        kind = e.get("kind")
        if kind == "launch":
            what = "resume" if e.get("resume") else "fresh start"
            rows.append([f"{e['t']:.0f}", e.get("attempt"), "launch",
                         e.get("resumed_from_step")
                         if e.get("resumed_from_step") is not None
                         else "-", what])
        elif kind == "restart":
            rows.append([f"{e['t']:.0f}", e.get("attempt"), "restart",
                         e.get("checkpoint_step")
                         if e.get("checkpoint_step") is not None else "-",
                         f"{e.get('reason', '?')} "
                         f"(backoff {e.get('backoff_s', '?')}s)"])
        elif kind == "give_up":
            rows.append([f"{e['t']:.0f}", "-", "GIVE UP", "-",
                         f"{e.get('reason', '?')} after "
                         f"{e.get('attempts', '?')} attempt(s)"])
    launches = sum(1 for e in events if e.get("kind") == "launch")
    restarts = sum(1 for e in events if e.get("kind") == "restart")
    head = (f"supervisor trail ({launches} launch(es), "
            f"{restarts} restart(s))")
    if not rows:
        return head + ": no launch events (did the supervisor start?)"
    return head + "\n" + _table(
        rows, ["t", "attempt", "event", "ckpt/resume step", "detail"])


def _policy_groups_block(evs) -> str:
    """Per-group policy resolutions (``policy_group`` events, PR 19)."""
    rows = []
    for e in evs[:64]:
        rows.append([e.get("group") or "?",
                     (e.get("clause") or "")[:36],
                     "locked" if e.get("locked") else "resolved",
                     e.get("provenance") or "?",
                     e.get("value") if e.get("value") is not None
                     else "-"])
    return "per-group policy decisions:\n" + _table(
        rows, ["group", "clause", "how", "provenance", "Mcells/s"])


def _group_chunks_block(evs) -> str:
    """Coupled-run per-group throughput (``group_chunk`` events)."""
    by_group: dict = {}
    for e in evs:
        by_group.setdefault(e.get("group") or "?", []).append(e)
    rows = []
    for g in sorted(by_group):
        recs = by_group[g]
        last = recs[-1]
        vals = [r.get("mcells_per_s") for r in recs
                if isinstance(r.get("mcells_per_s"), (int, float))]
        ready = [r.get("ready_ms_per_step") for r in recs
                 if isinstance(r.get("ready_ms_per_step"), (int, float))]
        rows.append([
            g, last.get("op") or "-", len(recs),
            round(sum(vals) / len(vals), 3) if vals else "-",
            last.get("mcells_per_s") if last.get("mcells_per_s")
            is not None else "-",
            round(sum(ready) / len(ready), 3) if ready else "-"])
    return (f"coupled groups ({len(by_group)}):\n"
            + _table(rows, ["group", "op", "chunks", "mean Mc/s",
                            "last Mc/s", "ready ms/step"]))


def _migrate_block(evs) -> str:
    """Live-migration trail (``migrate`` events: policy adoptions)."""
    rows = []
    for e in evs[:64]:
        dst = e.get("dst") or {}
        mesh = dst.get("mesh")
        rows.append([e.get("step", "-"), e.get("n", "-"),
                     (e.get("label") or "?")[:36],
                     e.get("provenance") or "?",
                     "x".join(map(str, mesh)) if mesh else "-",
                     e.get("rounds", "-")])
    return "migrations:\n" + _table(
        rows, ["step", "n", "label", "provenance", "dst mesh", "rounds"])


def _group_health_block(evs) -> str:
    """Group-named numerics verdicts of a coupled ``--health`` run."""
    rows = [[f"{e['t']:.0f}", e.get("group") or "-", e.get("step", "-"),
             e.get("verdict"), (e.get("reason") or "")[:56]]
            for e in evs[:128]]
    return "group health verdicts:\n" + _table(
        rows, ["t", "group", "step", "verdict", "reason"])


def _anomaly_block(evs) -> str:
    """Run-doctor findings (``anomaly`` events, obs/anomaly.py)."""
    rows = []
    for e in evs[:200]:
        sus = e.get("suspect") or {}
        who = f"{sus.get('kind', '-')}:{sus.get('name', '-')}"
        if sus.get("lag_ratio"):
            who += f" x{sus['lag_ratio']}"
        rows.append([e.get("chunk", "-"), e.get("anomaly") or "?",
                     e.get("severity") or "?", who,
                     json.dumps(e.get("evidence") or {},
                                sort_keys=True)[:64]])
    return (f"run-doctor findings ({len(evs)}) — verdict DEGRADED:\n"
            + _table(rows, ["chunk", "anomaly", "severity", "suspect",
                            "evidence"]))


def render(path: str) -> str:
    manifest, events = obs_trace.read_log(path)
    by_kind: dict = {}
    for e in events:
        by_kind.setdefault(e.get("kind"), []).append(e)
    out = [_manifest_block(manifest)]

    if manifest.get("tool") == "supervisor":
        # a supervisor log has no chunks to attribute — render the
        # restart trail, then the generic summary/heartbeat blocks
        out.append(_supervisor_trail_block(events))
        summary = (by_kind.get("summary") or [None])[-1]
        if summary:
            bits = [f"{k}={summary[k]}" for k in
                    ("ok", "attempts", "restarts", "gave_up",
                     "resumed_from_step") if k in summary]
            out.append("supervisor summary: " + "  ".join(bits))
        errors = by_kind.get("error") or []
        for e in errors:
            out.append(f"ERROR: {e.get('error')}")
        if not summary and not errors:
            out.append("(no summary event — the supervisor is live or "
                       "was killed; the trail above is the state)")
        return "\n\n".join(out)

    cost = (by_kind.get("costmodel") or [None])[-1]
    summary = (by_kind.get("summary") or [None])[-1]
    if cost:
        out.append(_attribution_block(cost, summary))
        cc = cost.get("budget_crosscheck")
        if cc:
            out.append(
                f"budget cross-check: slab operands "
                f"{_fmt_bytes(cc['slab_operand_bytes'])} vs budget.py "
                f"{_fmt_bytes(cc['budget_bytes'])} — "
                + ("MATCH" if cc.get("match") else "MISMATCH (models "
                   "drifted; fix before trusting either)"))
    profs = by_kind.get("profile") or []
    if profs:
        out.append(_profile_block(profs[-1], cost))
    if summary:
        out.append(_runtime_block(summary))

    beats = by_kind.get("heartbeat") or []
    if beats:
        out.append("heartbeat verdicts:\n" + _table(
            [[f"{b['t']:.0f}", b.get("verdict"),
              (b.get("detail") or "")[:70]] for b in beats],
            ["t", "verdict", "detail"]))
    # coupled-group vocabulary (PR 18/19): per-group policy decisions,
    # per-group throughput, the migration trail, group-named health
    for kind, block in (("policy_group", _policy_groups_block),
                        ("group_chunk", _group_chunks_block),
                        ("migrate", _migrate_block)):
        evs = by_kind.get(kind) or []
        if evs:
            out.append(block(evs))
    ghealth = [h for h in (by_kind.get("health") or [])
               if h.get("group")]
    if ghealth:
        out.append(_group_health_block(ghealth))
    anomalies = by_kind.get("anomaly") or []
    if anomalies:
        out.append(_anomaly_block(anomalies))
    labels = (by_kind.get("label") or []) + (by_kind.get("rung") or [])
    if labels:
        rows = []
        for e in labels[:200]:
            rows.append([
                e.get("label") or "x".join(map(str, e.get("mesh", []))),
                e.get("status") or e.get("mode") or "",
                e.get("mcells_per_s") if e.get("mcells_per_s")
                is not None else "-",
                (e.get("error") or "")[:48]])
        out.append(f"records ({len(labels)}):\n"
                   + _table(rows, ["label/mesh", "status", "Mcells/s",
                                   "error"]))
    results = by_kind.get("result") or []  # bench.py's headline record
    for e in results:
        out.append("bench result: " + "  ".join(
            f"{k}={e[k]}" for k in ("metric", "value", "unit",
                                    "vs_baseline", "compute", "backend")
            if k in e))
    errors = by_kind.get("error") or []
    for e in errors:
        out.append(f"ERROR: {e.get('error')}")
    if not summary and not errors and not results:
        out.append("(no summary event — the run is live or died without "
                   "an epilogue; heartbeat verdicts above say which)")
    return "\n\n".join(out)


def render_bundle(bundle) -> str:
    """Render a flight-recorder bundle (obs/flightrec.py): the whole
    post-mortem from ONE self-contained file — no telemetry dir, no
    ledger, no live process needed."""
    head = (f"flight bundle  schema={bundle.get('schema')}  "
            f"reason={bundle.get('reason')}  "
            f"verdict={bundle.get('verdict')}")
    out = [head, _manifest_block(bundle["manifest"])]
    events = bundle.get("events") or []
    kinds: dict = {}
    for e in events:
        k = e.get("kind") or "?"
        kinds[k] = kinds.get(k, 0) + 1
    out.append(f"ring: last {len(events)} of "
               f"{bundle.get('events_seen')} events  "
               + "  ".join(f"{k}={v}" for k, v in sorted(kinds.items())))
    anomalies = bundle.get("anomalies") or []
    if anomalies:
        out.append(_anomaly_block(anomalies))
    spans = bundle.get("open_spans") or []
    if spans:
        out.append("open spans at capture (outermost first):\n" + _table(
            [[s.get("span_id") or "-", s.get("name") or "-",
              f"{s.get('start', 0):.0f}"] for s in spans],
            ["span", "name", "start"]))
    best = bundle.get("best_known")
    if best:
        out.append("ledger best_known for this label: "
                   f"{best.get('value')} {best.get('unit')} "
                   f"(source {best.get('source')})")
    tunnel = bundle.get("tunnel") or {}
    out.append(f"tunnel: {tunnel.get('verdict', '?')}"
               + (f" — {tunnel.get('detail')}" if tunnel.get("detail")
                  else ""))
    env = bundle.get("env") or {}
    if env:
        out.append("env: " + "  ".join(f"{k}={v}" for k, v in
                                       sorted(env.items())))
    sib = bundle.get("sibling_events") or {}
    for src in sorted(sib):
        recs = [e for e in sib[src] if isinstance(e, dict)]
        skinds: dict = {}
        for e in recs:
            k = e.get("kind") or "?"
            skinds[k] = skinds.get(k, 0) + 1
        out.append(f"sibling {src} (tail): {len(recs)} events  "
                   + "  ".join(f"{k}={v}"
                               for k, v in sorted(skinds.items())))
    errors = [e for e in events if e.get("kind") == "error"]
    for e in errors:
        out.append(f"ERROR: {e.get('error')}")
    return "\n\n".join(out)


def _ledger_summary(path) -> str:
    """``--ledger``: campaign state in one command.

    The ``best_known`` table per label x backend (structurally unable
    to surface a quarantined row) plus the quarantine counts and
    reasons — what used to take hand-grepping benchmarks/ledger.jsonl.
    """
    from mpi_cuda_process_tpu.obs import ledger as ledger_lib

    path = path or ledger_lib.default_ledger_path()
    rows = ledger_lib.read_rows(path)
    best = ledger_lib.best_known(rows)
    quarantined = [r for r in rows if r.get("status") == "quarantined"]
    out = [f"ledger {path}: {len(rows)} rows "
           f"({len(quarantined)} quarantined), "
           f"{len(best)} best-known baselines"]
    trows = []
    for bk in sorted(best):
        r = best[bk]
        q = sum(1 for row in quarantined
                if ledger_lib.baseline_key(row) == bk)
        ts = r.get("measured_at")
        trows.append([bk, r["value"], r["unit"],
                      time.strftime("%Y-%m-%d",
                                    time.localtime(ts)) if ts else "-",
                      q, r["source"][:44]])
    if trows:
        out.append(_table(trows, ["label|backend", "best", "unit",
                                  "measured", "quarantined", "source"]))
    reasons: dict = {}
    for r in quarantined:
        key = str(r.get("quarantine") or "?").split(":")[0]
        reasons[key] = reasons.get(key, 0) + 1
    if reasons:
        out.append("quarantine reasons:\n" + "\n".join(
            f"  {n:4d}  {reason}"
            for reason, n in sorted(reasons.items(),
                                    key=lambda kv: -kv[1])))
    return "\n\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("log", nargs="?", default=None,
                    help="telemetry JSONL path (or, with --ledger, a "
                         "ledger path; defaults to the committed "
                         "benchmarks/ledger.jsonl there)")
    ap.add_argument("--check", action="store_true",
                    help="validate the manifest and every event against "
                         "the shared schema; exit nonzero on any "
                         "invalid record (the tier-1 smoke mode)")
    ap.add_argument("--ledger", action="store_true",
                    help="summary mode for a campaign ledger: the "
                         "best_known table per label x backend plus "
                         "quarantine counts + reasons")
    a = ap.parse_args(argv)
    if a.ledger:
        try:
            print(_ledger_summary(a.log))
        except (ValueError, OSError) as e:
            print(f"obs_report --ledger: {e}", file=sys.stderr)
            return 1
        return 0
    if not a.log:
        ap.error("a telemetry JSONL path is required (or use --ledger)")
    from mpi_cuda_process_tpu.obs import flightrec as flightrec_lib
    if flightrec_lib.is_bundle_file(a.log):
        # a flight-recorder bundle IS the post-mortem: render it even
        # when the telemetry dir it came from no longer exists
        try:
            bundle = flightrec_lib.read_bundle(a.log)
        except (ValueError, OSError) as e:
            print(f"obs_report: bad bundle: {e}", file=sys.stderr)
            return 1
        if a.check:
            try:
                flightrec_lib.validate_bundle(bundle)
            except ValueError as e:
                print(f"obs_report --check: INVALID: {e}",
                      file=sys.stderr)
                return 1
            print("obs_report --check: ok (flight bundle, "
                  f"reason={bundle.get('reason')}, "
                  f"{len(bundle.get('events') or [])} events)")
        print(render_bundle(bundle))
        return 0
    if a.check:
        # the pallas auto-retry writes its own log at PATH.retry.jsonl
        # (cli.run); when present it must pass the same schema — a
        # sibling drifting off-schema is the same gate failure
        to_check = [a.log]
        retry = a.log + ".retry.jsonl"
        if os.path.exists(retry):
            to_check.append(retry)
        for path in to_check:
            try:
                manifest, events = obs_trace.validate_log(path)
            except (ValueError, OSError) as e:
                print(f"obs_report --check: INVALID: {e}", file=sys.stderr)
                return 1
            print(f"obs_report --check: ok (tool={manifest['tool']}, "
                  f"schema={manifest['schema']}, {len(events)} events"
                  + (", retry sibling" if path != a.log else "") + ")")
    print(render(a.log))
    return 0


if __name__ == "__main__":
    sys.exit(main())
