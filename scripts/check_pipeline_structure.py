#!/usr/bin/env python
"""Jaxpr-structure gate for the pipelined halo exchange (tier1.sh).

Value equivalence is covered by the test suite; THIS gate pins the
dependency-structure claims the perf work rests on (they can regress
with every number bit-identical): one exchange round per scan iteration,
and the two-sided interior/exchange independence that lets XLA hide the
exchange behind a full interior pass.  The shared implementation lives
in ``mpi_cuda_process_tpu/utils/jaxprcheck.py`` (also used by
tests/test_pipeline_fused.py); this wrapper forces the CPU backend with
virtual devices (the cpuforce recipe) and runs the check on a z-only and
a 2-axis mesh.  Trace-only — a few seconds, no kernel executes.

``--exchange rdma`` runs the remote-DMA leg instead: the same pipelined
assertions against the rdma exchange equations, PLUS the zero-ppermute
gate on the whole step in both build modes — interpret (what tier-1
executes) and compiled (zero XLA collective anywhere, the exchange
carried as remote ``dma_start`` eqns inside the collective kernels).

``--ensemble N`` runs the batched-engine leg: the N-member batched step
must issue EXACTLY the unbatched step's exchange-round count (the
member axis rides inside each collective operand — the fixed-cost
amortization the ensemble engine exists for), on a z-only and a 2-axis
mesh, ppermute and rdma transports.  tier1.sh runs all three legs.
"""

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from cpuforce import force_cpu  # noqa: E402

force_cpu(8)

_CASES = {
    "ppermute": [
        # z-only ring, pad-free z-slab kernel
        dict(stencil_name="heat3d", grid=(32, 16, 128),
             mesh_shape=(2, 1, 1), k=4, padfree=True),
        # 2-axis mesh: y shells + two-hop corner ppermutes too
        dict(stencil_name="heat3d", grid=(32, 32, 128),
             mesh_shape=(2, 2, 1), k=4, padfree=True),
    ],
    "rdma": [
        # z-only ring, streaming kernel, in-kernel remote-DMA exchange
        dict(stencil_name="heat3d", grid=(48, 32, 128),
             mesh_shape=(2, 1, 1), k=4, exchange="rdma"),
        # 2-axis mesh: y slabs + two-hop corner rings through the
        # transport too
        dict(stencil_name="heat3d", grid=(48, 32, 128),
             mesh_shape=(2, 2, 1), k=4, exchange="rdma"),
    ],
}


_ENSEMBLE_CASES = [
    dict(stencil_name="heat3d", grid=(32, 16, 128),
         mesh_shape=(2, 1, 1), k=4, padfree=True),
    dict(stencil_name="heat3d", grid=(32, 32, 128),
         mesh_shape=(2, 2, 1), k=4, padfree=True),
    dict(stencil_name="heat3d", grid=(96, 32, 128),
         mesh_shape=(2, 1, 1), k=4, exchange="rdma"),
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--exchange", default="ppermute",
                    choices=["ppermute", "rdma"],
                    help="which exchange transport's structural "
                         "contract to pin (tier1.sh runs both legs)")
    ap.add_argument("--ensemble", type=int, default=0, metavar="N",
                    help="run the batched-engine leg instead: the "
                         "N-member step's exchange-round count must "
                         "equal the unbatched step's (both transports, "
                         "both mesh families)")
    a = ap.parse_args(argv)

    if a.ensemble:
        from mpi_cuda_process_tpu.utils.jaxprcheck import (
            check_ensemble_structure,
        )

        for case in _ENSEMBLE_CASES:
            rep = check_ensemble_structure(ensemble=a.ensemble, **case)
            print(f"check_ensemble_structure[{case.get('exchange', 'ppermute')}]"
                  f": ok {case['mesh_shape']} N={a.ensemble} "
                  f"(exchange-rounds batched="
                  f"{rep['n_exchange_batched']} == single="
                  f"{rep['n_exchange_single']})")
        return 0

    from mpi_cuda_process_tpu.utils.jaxprcheck import (
        check_pipeline_structure,
    )

    for case in _CASES[a.exchange]:
        rep = check_pipeline_structure(**case)
        line = (f"check_pipeline_structure[{a.exchange}]: ok "
                f"{case['mesh_shape']} "
                f"(exchange-rounds/iter={rep['n_ppermute']}, "
                f"interior->exchange={rep['interior_depends_on_exchange']}, "
                f"exchange->interior={rep['exchange_depends_on_interior']}")
        if a.exchange == "rdma":
            line += (f", compiled remote-dma={rep['compiled']['n_remote_dma']}"
                     f", compiled ppermute={rep['compiled']['n_ppermute']}")
        print(line + ")")
    return 0


if __name__ == "__main__":
    sys.exit(main())
