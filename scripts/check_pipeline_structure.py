#!/usr/bin/env python
"""Jaxpr-structure gate for the pipelined halo exchange (tier1.sh).

Value equivalence is covered by the test suite; THIS gate pins the
dependency-structure claims the perf work rests on (they can regress
with every number bit-identical): one exchange round per scan iteration,
and the two-sided interior/exchange independence that lets XLA hide the
exchange behind a full interior pass.  The shared implementation lives
in ``mpi_cuda_process_tpu/utils/jaxprcheck.py`` (also used by
tests/test_pipeline_fused.py); this wrapper forces the CPU backend with
virtual devices (the cpuforce recipe) and runs the check on a z-only and
a 2-axis mesh.  Trace-only — a few seconds, no kernel executes.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from cpuforce import force_cpu  # noqa: E402

force_cpu(8)


def main() -> int:
    from mpi_cuda_process_tpu.utils.jaxprcheck import (
        check_pipeline_structure,
    )

    cases = [
        # z-only ring, pad-free z-slab kernel
        dict(stencil_name="heat3d", grid=(32, 16, 128),
             mesh_shape=(2, 1, 1), k=4, padfree=True),
        # 2-axis mesh: y shells + two-hop corner ppermutes too
        dict(stencil_name="heat3d", grid=(32, 32, 128),
             mesh_shape=(2, 2, 1), k=4, padfree=True),
    ]
    for case in cases:
        rep = check_pipeline_structure(**case)
        print(f"check_pipeline_structure: ok {case['mesh_shape']} "
              f"(ppermutes/iter={rep['n_ppermute']}, "
              f"interior->exchange={rep['interior_depends_on_exchange']}, "
              f"exchange->interior={rep['exchange_depends_on_interior']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
