#!/usr/bin/env python
"""Bounded, time-boxed CLIENT-SIDE diagnosis of a wedged TPU tunnel.

VERDICT r5 weak #1 / next #2: through ~40 h of cumulative wedge the only
response was a passive probe loop — nobody determined whether the wedge
is client-side or server-side, whether a fresh process with a clean JAX
cache behaves differently, or WHICH layer hangs.  This script converts
docs/STATE.md's H3 ("half-healthy compile service") from a hypothesis
into a finding (or an eliminated hypothesis) by running a ladder of
probes, each in a FRESH subprocess with a hard timeout and its stderr —
the tunnel client's own error channel — captured:

  cpu_control      CPU-forced trivial op: distinguishes "this machine /
                   python env is broken" from "the tunnel is broken".
                   Must pass for any other verdict to mean anything.
  discovery        ``import jax; jax.default_backend()`` under the
                   default (axon sitecustomize) environment: does
                   backend/session discovery itself hang?
  discovery_clean  the same probe with a FRESH JAX compilation cache
                   (JAX_COMPILATION_CACHE_DIR -> empty temp dir, the
                   persistent-cache env knobs cleared): a divergence
                   from ``discovery`` implicates client-side cache
                   state, which a process restart would NOT clear.
  execute          a trivial device op (``jnp.add(1, 1)``): the
                   dispatch/execute layer past discovery.
  compile          ``jax.jit`` of a tiny fresh function (a random
                   constant baked in so no cache can serve it): the
                   remote-compile layer — H3's suspect.

Every probe is bounded (default 120 s — far above the ~66 ms healthy
round-trip, far below the outer harness budgets), so the WORST case is
~10 minutes, never a hang.  The ladder stops early once a layer hangs
(running more probes against a wedged tunnel risks deepening the wedge;
everything below the first hang is unreachable anyway).

Output: a human-readable report on stderr, one JSON line on stdout, and
``--append-state`` appends a timestamped findings section to
docs/STATE.md so the diagnosis lands where the next session reads it.

Do NOT run this concurrently with another TPU process (the 2026-07-29
two-process wedge, docs/STATE.md infra gotchas).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_STATE = os.path.join(_REPO, "docs", "STATE.md")

# (name, code, needs_clean_cache, forces_cpu)
_PROBES = [
    ("cpu_control",
     "import jax; jax.config.update('jax_platforms', 'cpu'); "
     "import jax.numpy as jnp; print('OK', int(jnp.add(1, 1)))",
     False, True),
    ("discovery",
     "import jax; print('OK', jax.default_backend(), len(jax.devices()))",
     False, False),
    ("discovery_clean",
     "import jax; print('OK', jax.default_backend(), len(jax.devices()))",
     True, False),
    ("execute",
     "import jax, jax.numpy as jnp; "
     "print('OK', jax.default_backend(), int(jnp.add(1, 1)))",
     False, False),
    ("compile",
     # a fresh constant per invocation: no persistent cache can serve it,
     # so this exercises the REMOTE COMPILE path every time
     "import os, jax, jax.numpy as jnp; c = float(os.getpid() % 997); "
     "f = jax.jit(lambda x: x * c + 1.0); "
     "print('OK', jax.default_backend(), float(f(jnp.float32(2.0))))",
     False, False),
]


def _run_probe(name, code, clean_cache, force_cpu, timeout_s):
    env = dict(os.environ)
    tmp = None
    if clean_cache:
        tmp = tempfile.mkdtemp(prefix="jax_clean_cache_")
        env["JAX_COMPILATION_CACHE_DIR"] = tmp
        # clear every persistent-cache knob the client might read
        for k in list(env):
            if "CACHE" in k and k.startswith(("JAX_", "LIBTPU_")) \
                    and k != "JAX_COMPILATION_CACHE_DIR":
                env.pop(k)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    t0 = time.monotonic()
    rec = {"probe": name, "timeout_s": timeout_s}
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True,
            text=True, timeout=timeout_s, cwd=_REPO)
        rec["wall_s"] = round(time.monotonic() - t0, 2)
        rec["rc"] = proc.returncode
        rec["ok"] = proc.returncode == 0 and "OK" in proc.stdout
        rec["stdout"] = proc.stdout.strip()[-400:]
        # the tunnel client's own error channel — the piece no previous
        # round ever captured
        rec["stderr_tail"] = proc.stderr.strip()[-1500:]
    except subprocess.TimeoutExpired as e:
        rec["wall_s"] = round(time.monotonic() - t0, 2)
        rec["ok"] = False
        rec["hang"] = True
        rec["stderr_tail"] = ((e.stderr or b"").decode("utf-8", "replace")
                              if isinstance(e.stderr, bytes)
                              else (e.stderr or ""))[-1500:]
    except Exception as e:  # noqa: BLE001 — a diagnosis must not crash
        rec["wall_s"] = round(time.monotonic() - t0, 2)
        rec["ok"] = False
        rec["error"] = f"{type(e).__name__}: {e}"
    return rec


def _classify(results):
    """Map the probe ladder to a layer verdict (the H1/H2/H3 language of
    docs/STATE.md)."""
    r = {rec["probe"]: rec for rec in results}

    def hung(name):
        return name in r and r[name].get("hang")

    def ok(name):
        return name in r and r[name].get("ok")

    if not ok("cpu_control"):
        return ("ENVIRONMENT", "the CPU control probe failed — this "
                "machine/python env is broken independent of the tunnel; "
                "no tunnel verdict is possible")
    if ok("discovery") and \
            "tpu" not in r["discovery"].get("stdout", ""):
        return ("NO_TPU", "backend discovery succeeds but reports a "
                "non-TPU backend — no tunnel is visible from this box; "
                "nothing to diagnose (the CPU-probe ladder still "
                "validates the tool end-to-end)")
    if hung("discovery") and hung("discovery_clean"):
        return ("SESSION_LAYER", "backend discovery hangs with AND "
                "without a clean JAX cache — the wedge lives at the "
                "tunnel session/discovery layer, server-side or "
                "connection-level; a client cache purge would not help")
    if hung("discovery") and ok("discovery_clean"):
        return ("CLIENT_CACHE", "discovery hangs under the default cache "
                "but succeeds with a fresh one — CLIENT-side cache state "
                "is implicated; purge the JAX compilation cache dir")
    if ok("discovery") and hung("execute"):
        return ("EXECUTE_LAYER", "discovery succeeds but a trivial "
                "device op hangs — the wedge is in dispatch/execute, "
                "past session setup")
    if ok("execute") and hung("compile"):
        return ("COMPILE_LAYER", "trivial ops execute but a fresh jit "
                "compile hangs — STATE.md H3 (half-healthy compile "
                "service) is now a FINDING, not a hypothesis")
    if ok("compile"):
        return ("HEALTHY", "every layer answered within budget — the "
                "tunnel is healthy right now (run the campaign)")
    return ("INCONCLUSIVE", "probe pattern fits no single layer — read "
            "the per-probe stderr tails")


def _state_section(verdict, detail, results, started):
    ts = datetime.datetime.fromtimestamp(started).strftime(
        "%Y-%m-%d %H:%M")
    lines = [
        "",
        f"## Tunnel wedge diagnosis ({ts}, scripts/diagnose_tunnel.py)",
        "",
        f"- **Verdict: {verdict}** — {detail}",
        "- Probe ladder (fresh subprocess each, hard timeout, stderr "
        "captured):",
        "",
        "| probe | result | wall s | stderr tail (last line) |",
        "|---|---|---:|---|",
    ]
    for rec in results:
        if rec.get("hang"):
            res = "HANG"
        elif rec.get("ok"):
            res = "ok"
        else:
            res = f"fail rc={rec.get('rc', '?')}"
        tail = (rec.get("stderr_tail") or "").strip().splitlines()
        tail = tail[-1][:90].replace("|", "\\|") if tail else ""
        lines.append(f"| {rec['probe']} | {res} | {rec.get('wall_s', 0)} "
                     f"| {tail} |")
    lines.append("")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--timeout", type=float, default=120.0,
                   help="per-probe hard timeout in seconds (default 120; "
                        "total worst case = n_probes x timeout)")
    p.add_argument("--append-state", action="store_true",
                   help="append the findings section to docs/STATE.md")
    p.add_argument("--json-out", default=None,
                   help="also write the full JSON record to this path")
    a = p.parse_args(argv)

    started = time.time()
    results = []
    for name, code, clean, cpu in _PROBES:
        print(f"[diagnose] probe {name} (<= {a.timeout:.0f}s) ...",
              file=sys.stderr)
        rec = _run_probe(name, code, clean, cpu, a.timeout)
        results.append(rec)
        state = ("HANG" if rec.get("hang")
                 else "ok" if rec.get("ok") else "fail")
        print(f"[diagnose]   -> {state} in {rec.get('wall_s')}s",
              file=sys.stderr)
        if rec.get("hang") and name != "discovery":
            # stop after the first hang past the discovery pair: deeper
            # probes are unreachable, and piling processes onto a wedged
            # tunnel is how wedges deepen
            break
        if name == "cpu_control" and not rec.get("ok"):
            break

    verdict, detail = _classify(results)
    record = {"tool": "diagnose_tunnel", "started_at": started,
              "verdict": verdict, "detail": detail, "probes": results}
    print(json.dumps(record))
    print(f"[diagnose] VERDICT: {verdict} — {detail}", file=sys.stderr)
    if a.json_out:
        with open(a.json_out, "w") as fh:
            json.dump(record, fh, indent=1)
    if a.append_state:
        with open(_STATE, "a") as fh:
            fh.write(_state_section(verdict, detail, results, started))
        print(f"[diagnose] findings appended to {_STATE}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
