#!/usr/bin/env bash
# Tier-1 verification gate — the EXACT command from ROADMAP.md, committed
# so builder and reviewer run the identical gate (a hand-retyped variant
# that drops a flag is how "passes for me" diverges from "passes the
# driver").  Runs the default-tier test suite on the CPU backend (8
# virtual devices via tests/conftest.py) and prints the passed-dot count
# the driver scores.  Afterwards, the collection-count guard
# (scripts/check_tier_counts.py) verifies pytest.ini's tier-counts line
# against reality — the stale-count drift class cannot recur silently;
# its failure fails this script too (the driver's raw ROADMAP command is
# unaffected).
#
# Usage: bash scripts/tier1.sh   (from the repo root)
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
python scripts/check_tier_counts.py || rc=1
# Dependency-structure gate for the pipelined halo exchange: trace-only
# (seconds); the perf claims it pins can regress with every value test
# still green (see scripts/check_pipeline_structure.py).
python scripts/check_pipeline_structure.py || rc=1
# The remote-DMA leg of the same gate: zero XLA ppermute in the rdma
# step (interpret AND compiled traces), exchange rounds preserved by
# the slab carry, two-sided interior independence.  Trace-only.
python scripts/check_pipeline_structure.py --exchange rdma || rc=1
# The batched-ensemble leg (round 15): the N-member batched step must
# issue EXACTLY the unbatched step's exchange-round count (the member
# axis rides inside each collective operand — one exchange round per
# pass regardless of N), on both mesh families and both transports.
python scripts/check_pipeline_structure.py --ensemble 4 || rc=1
# Batched-ensemble smoke: a CPU run with --ensemble 2 on a 2-device
# mesh must execute the batched sharded stepper end-to-end, emit a
# schema-valid manifest whose chunk records carry the member count, and
# report AGGREGATE + per-member throughput in the status payload (a
# batched run must be distinguishable from a fast single run).
rm -f /tmp/_t1_ens.jsonl
timeout -k 10 300 python -c "
import json
from cpuforce import force_cpu; force_cpu(8)
from mpi_cuda_process_tpu import cli
from mpi_cuda_process_tpu.obs.metrics import RunMetrics
fields, _ = cli.run(cli.config_from_args(
    ['--stencil', 'heat3d', '--grid', '32,16,128', '--iters', '8',
     '--mesh', '2,1,1', '--ensemble', '2', '--log-every', '2',
     '--telemetry', '/tmp/_t1_ens.jsonl']))
assert fields[0].shape == (2, 32, 16, 128), fields[0].shape
rm = RunMetrics()
recs = [json.loads(l) for l in open('/tmp/_t1_ens.jsonl') if l.strip()]
for r in recs:
    rm.ingest(r)
chunks = [r for r in recs if r.get('kind') == 'chunk']
assert chunks and all(c.get('members') == 2 for c in chunks), chunks
tp = rm.status()['throughput']
assert tp.get('ensemble') == 2 and 'gcells_per_s' in tp \
    and 'gcells_per_s_per_member' in tp, tp
assert rm.registry.snapshot()['obs_ensemble_size']['value'] == 2
print('ensemble smoke ok: %.4f Gcells/s aggregate, %.4f /member' % (
    tp['gcells_per_s'], tp['gcells_per_s_per_member']))
" || rc=1
timeout -k 10 120 python scripts/obs_report.py /tmp/_t1_ens.jsonl --check \
  > /dev/null || rc=1
# Interpret-mode rdma smoke: a sharded CLI run with --exchange rdma
# executes the remote-DMA kernels end-to-end on the CPU backend (the
# loopback VMEM-ring path, honestly tagged 'interpret-emulated' in the
# manifest's exchange event) and the manifest must validate.
rm -f /tmp/_t1_rdma.jsonl
timeout -k 10 300 python -c "
from cpuforce import force_cpu; force_cpu(8)
from mpi_cuda_process_tpu import cli
cli.run(cli.config_from_args(
    ['--stencil', 'heat3d', '--grid', '48,32,128', '--iters', '8',
     '--mesh', '2,1,1', '--fuse', '4', '--fuse-kind', 'stream',
     '--exchange', 'rdma', '--telemetry', '/tmp/_t1_rdma.jsonl']))
" || rc=1
timeout -k 10 120 python scripts/obs_report.py /tmp/_t1_rdma.jsonl --check \
  > /dev/null || rc=1
# Telemetry + profile smoke: a CPU CLI run must emit a schema-valid
# manifest (with a chunk-scoped --profile whose attribution degrades
# HONESTLY on CPU — 'unavailable', never zeros) and obs_report must
# validate + render it (the shared-schema guarantee of
# mpi_cuda_process_tpu/obs — all four entry points emit what this
# validator accepts, so the gate a builder runs checks the schema too).
rm -f /tmp/_t1_obs.jsonl /tmp/_t1_ledger.jsonl
rm -rf /tmp/_t1_prof
timeout -k 10 180 python -c "
from cpuforce import force_cpu; force_cpu()
from mpi_cuda_process_tpu import cli
cli.run(cli.config_from_args(
    ['--stencil', 'heat2d', '--grid', '32,128', '--iters', '8',
     '--log-every', '2', '--telemetry', '/tmp/_t1_obs.jsonl',
     '--profile', '/tmp/_t1_prof']))
" || rc=1
timeout -k 10 120 python scripts/obs_report.py /tmp/_t1_obs.jsonl --check \
  > /dev/null || rc=1
# Supervisor smoke (resilience/): a CPU run with an injected mid-run
# wedge (FAULT_INJECT=exchange:step=40:hang) must be detected by the
# supervisor's wall-clock watchdog, killed, relaunched with --resume
# from the surviving step-30 checkpoint, and completed — with the
# restart and the resumed_from_step landing in the supervisor's own
# schema-valid obs log.  The bit-exactness of the resumed state is
# pinned by the default-tier tests; this smoke pins the end-to-end
# CLI-mode loop every build.
rm -rf /tmp/_t1_sup
timeout -k 10 240 env FAULT_INJECT='exchange:step=40:hang' \
  FAULT_HANG_S=120 python -c "
import json
from cpuforce import force_cpu; force_cpu()
from mpi_cuda_process_tpu.config import RunConfig
from mpi_cuda_process_tpu.resilience import supervisor as sup
rc = sup.run_supervised(RunConfig(
    stencil='life', grid=(64, 64), iters=100, seed=7,
    checkpoint_every=10, checkpoint_dir='/tmp/_t1_sup/ck',
    telemetry='/tmp/_t1_sup/run.jsonl', supervise=True,
    max_restarts=2, restart_backoff=0.3, supervise_stall_s=8.0))
assert rc == 0, f'supervisor rc={rc}'
evs = [json.loads(l)
       for l in open('/tmp/_t1_sup/run.supervisor.jsonl') if l.strip()]
kinds = [e.get('kind') for e in evs]
assert 'restart' in kinds and 'give_up' not in kinds, kinds
resumed = [e.get('resumed_from_step') for e in evs
           if e.get('kind') == 'launch' and e.get('resume')]
assert resumed and resumed[0] == 30, evs
print('supervisor smoke ok: resumed_from_step', resumed[0])
" || rc=1
timeout -k 10 120 python scripts/obs_report.py \
  /tmp/_t1_sup/run.supervisor.jsonl --check > /dev/null || rc=1
# Span-trace export smoke (round 16): the supervised wedge run above
# left a supervisor log plus two attempt logs whose spans share ONE
# trace (OBS_TRACE_CONTEXT propagation).  The export must fold all
# three into a single Perfetto/Chrome JSON — the script schema-
# validates the event list itself before writing (nonzero exit on any
# problem) — and the leg asserts the causal claims: a single trace_id
# across supervisor and both child attempts, and a restart span
# carrying resumed_from_step=30 ordered BETWEEN the two attempt spans.
rm -f /tmp/_t1_trace.json
timeout -k 10 120 python scripts/obs_trace_export.py /tmp/_t1_sup/run.jsonl \
  -o /tmp/_t1_trace.json || rc=1
timeout -k 10 120 python -c "
import json
obj = json.load(open('/tmp/_t1_trace.json'))
spans = [e for e in obj['traceEvents']
         if e.get('ph') == 'X' and e.get('cat') == 'span']
tids = {e['args']['trace_id'] for e in spans}
assert len(tids) == 1, f'expected one trace_id, got {tids}'
files = {e['args']['file'] for e in spans}
need = {'run.supervisor.jsonl', 'run.attempt0.jsonl',
        'run.attempt1.jsonl'}
assert need <= files, f'spans missing from {need - files}'
attempts = sorted((e for e in spans if e['name'] == 'attempt'),
                  key=lambda e: e['ts'])
restart = [e for e in spans if e['name'] == 'restart'][0]
assert restart['args']['resumed_from_step'] == 30, restart['args']
assert attempts[0]['ts'] + attempts[0]['dur'] <= restart['ts'], \
    'restart span must start after attempt 0 ends'
assert restart['ts'] + restart['dur'] <= attempts[1]['ts'], \
    'restart span must end before attempt 1 starts'
print('span smoke ok: trace', tids.pop(), 'across', len(files),
      'logs,', len(spans), 'spans')
" || rc=1
# Health-sentinel smoke (obs/health.py, round 17): the E2E acceptance
# pin.  FAULT_INJECT=numerics:step=40:nan under --supervise --serve 0
# --health must (1) expose the sentinel's health block in /status.json
# scraped LIVE from the supervisor's aggregate console while the run
# is in flight, (2) rank the final verdict DIVERGED through the same
# served-console machinery (the kill-on-fatal shuts the live console
# down within ~100 ms of the DIVERGED event, so the terminal verdict
# is pinned by re-serving the run's own logs — catching the transient
# live was a race the original 64x64 leg lost on a fast machine),
# (3) make the supervisor give up WITHOUT a restart loop (give_up
# carrying the verdict, exactly one launch, no restart event), and
# (4) land the ledger row quarantined with reason 'diverged'.  The
# 4096^2 grid makes the pre-poison window (health@10 .. poison@40)
# ~2 s — wide enough that the live scrape is deterministic, not luck.
# obs_top --once on the child log must exit nonzero (the DIVERGED
# health-probe contract).
rm -rf /tmp/_t1_health
timeout -k 10 300 env FAULT_INJECT='numerics:step=40:nan' python -c "
import json, threading, time, urllib.request
from cpuforce import force_cpu; force_cpu()
from mpi_cuda_process_tpu import cli
from mpi_cuda_process_tpu.obs import ledger
from mpi_cuda_process_tpu.obs import serve as serve_lib
from mpi_cuda_process_tpu.resilience import supervisor as sup
tel = '/tmp/_t1_health/run.jsonl'
seen = {}
def scrape():
    url = None
    deadline = time.monotonic() + 120
    suplog = sup.sibling_path(tel, 'supervisor')
    while time.monotonic() < deadline and url is None:
        try:
            for line in open(suplog):
                rec = json.loads(line)
                if rec.get('kind') == 'serve':
                    url = rec['url']
        except (OSError, ValueError):
            pass
        if url is None:
            time.sleep(0.05)
    while time.monotonic() < deadline and 'live_health' not in seen:
        try:
            s = json.load(urllib.request.urlopen(url + '/status.json',
                                                 timeout=5))
            if (s.get('health') or {}).get('verdict'):
                seen['live_health'] = s
        except OSError:
            pass
        time.sleep(0.05)
t = threading.Thread(target=scrape); t.start()
rc = sup.run_supervised(cli.config_from_args(
    ['--stencil', 'heat2d', '--grid', '4096,4096', '--iters', '100',
     '--seed', '7', '--checkpoint-every', '10',
     '--checkpoint-dir', '/tmp/_t1_health/ck', '--telemetry', tel,
     '--health', '--supervise', '--max-restarts', '2',
     '--restart-backoff', '0.3', '--supervise-stall-s', '60',
     '--serve', '0']))
t.join()
assert rc == 1, f'supervisor rc={rc} (want give-up)'
s = seen.get('live_health')
assert s is not None, 'never saw a live health block in /status.json'
hv = s['health']['verdict']
assert hv in ('HEALTHY', 'DIVERGED'), hv
assert s.get('verdict') == ('DIVERGED' if hv == 'DIVERGED'
                            else 'ALIVE'), s.get('verdict')
suplog = sup.sibling_path(tel, 'supervisor')
att0 = sup.sibling_path(tel, 'attempt0')
with serve_lib.serve_aggregate([suplog, att0]) as srv:
    s2 = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        s2 = json.load(urllib.request.urlopen(srv.url + '/status.json',
                                              timeout=5))
        if s2.get('verdict') == 'DIVERGED':
            break
        time.sleep(0.1)
assert s2 and s2.get('verdict') == 'DIVERGED', (s2 or {}).get('verdict')
assert (s2.get('health') or {}).get('verdict') == 'DIVERGED', \
    s2.get('health')
evs = [json.loads(line) for line in open(suplog) if line.strip()]
kinds = [e.get('kind') for e in evs]
assert 'restart' not in kinds, kinds
assert len([e for e in evs if e.get('kind') == 'launch']) == 1, kinds
gu = [e for e in evs if e.get('kind') == 'give_up']
assert gu and gu[0].get('verdict') == 'DIVERGED', gu
rows = ledger.rows_from_log(att0)
assert rows and rows[-1]['status'] == 'quarantined' \
    and rows[-1]['quarantine'] == 'diverged', rows
print('health smoke ok: live health block (%s), DIVERGED on the'
      ' served console, give-up without restart, ledger row'
      ' quarantined(diverged)' % hv)
" || rc=1
timeout -k 10 120 python scripts/obs_report.py \
  /tmp/_t1_health/run.attempt0.jsonl --check > /dev/null || rc=1
# obs_top --once exits NONZERO on the diverged child log (the same CI
# probe contract as WEDGED/STALLED/give-up)
if timeout -k 10 120 python scripts/obs_top.py \
     /tmp/_t1_health/run.attempt0.jsonl --once > /dev/null; then
  echo 'obs_top --once must exit nonzero on a DIVERGED log' >&2; rc=1
fi
# Live-console smoke (obs/serve.py): a CPU run with --serve 0 must
# expose /metrics, /status.json, and an incremental /events?after=
# slice over stdlib urllib WHILE the run is in flight (the scraper
# discovers the bound address from the 'serve' event in the manifest
# log — the same discovery path a remote monitor uses), the status
# payload must carry a schema-valid manifest, and the server must shut
# down with the run: no leaked obs-serve thread, port closed.
rm -f /tmp/_t1_serve.jsonl
timeout -k 10 240 python -c "
import json, threading, time, urllib.request
from cpuforce import force_cpu; force_cpu()
from mpi_cuda_process_tpu import cli
from mpi_cuda_process_tpu.obs import trace
path = '/tmp/_t1_serve.jsonl'
res = {}
def scrape():
    url = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and url is None:
        try:
            for line in open(path):
                rec = json.loads(line)
                if rec.get('kind') == 'serve':
                    url = rec['url']
        except (OSError, ValueError):
            pass
        if url is None:
            time.sleep(0.05)
    if url is None:
        res['err'] = 'no serve event in the telemetry log'; return
    try:
        m = urllib.request.urlopen(url + '/metrics', timeout=10)
        res['metrics'] = m.read().decode()
        s = json.load(urllib.request.urlopen(url + '/status.json',
                                             timeout=10))
        trace.validate_manifest(s['manifest'])  # schema-valid payload
        for key in ('verdict', 'chunks_recent', 'heartbeat', 'restarts',
                    'throughput'):
            assert key in s, key
        assert s['manifest']['tool'] == 'cli'
        res['status'] = s
        ev = urllib.request.urlopen(url + '/events?after=0',
                                    timeout=10).read().decode()
        lines = [json.loads(l) for l in ev.strip().splitlines()]
        assert lines and lines[0]['kind'] == 'manifest', lines[:1]
        seqs = [l['_seq'] for l in lines]
        assert seqs == sorted(seqs) and seqs[0] == 1, seqs
        # incremental slice via the bounded long-poll: the serve and
        # costmodel events are already on disk, so waiting is bounded
        # by one poller cycle
        inc = urllib.request.urlopen(
            url + '/events?after=%d&wait=10' % seqs[0],
            timeout=20).read().decode()
        inc_lines = [json.loads(l) for l in inc.strip().splitlines()]
        assert inc_lines and inc_lines[0]['_seq'] == seqs[0] + 1, 'the '\
            'after= slice must start exactly one past the cursor'
        res['url'] = url
    except Exception as e:
        res['err'] = f'{type(e).__name__}: {e}'
t = threading.Thread(target=scrape); t.start()
cli.run(cli.config_from_args(
    ['--stencil', 'life', '--grid', '512,512', '--iters', '1500',
     '--log-every', '50', '--serve', '0',
     '--telemetry', path]))
t.join()
assert 'err' not in res, res.get('err')
assert 'obs_run_info' in res['metrics']
leaked = [th.name for th in threading.enumerate()
          if th.name.startswith('obs-serve')]
assert not leaked, f'leaked server threads after run exit: {leaked}'
try:
    urllib.request.urlopen(res['url'] + '/status.json', timeout=3)
    raise AssertionError('server still answering after run exit')
except OSError:
    pass
print('live-console smoke ok:', res['url'])
" || rc=1
timeout -k 10 120 python scripts/obs_report.py /tmp/_t1_serve.jsonl \
  --check > /dev/null || rc=1
# Serving smoke (round 18): the continuous-batching scheduler end to
# end — ONE resident engine, three queued jobs across TWO size
# classes, the scheduler block scraped live from /status.json mid-run,
# and an injected NaN (numerics fault site) that evicts ONLY the
# poisoned member slot with round 17's DIVERGED verdict while its
# co-tenant (same compiled step, adjacent slot) and the second class
# finish clean — the co-tenant bit-exact against its solo replay.
rm -rf /tmp/_t1_serving
timeout -k 10 300 env FAULT_INJECT='numerics:step=16:nan' python -c "
import json, os, threading, time, urllib.request
import numpy as np
from cpuforce import force_cpu; force_cpu(8)
from mpi_cuda_process_tpu import cli, serving
from mpi_cuda_process_tpu.config import RunConfig
from mpi_cuda_process_tpu.obs.health import SimulationDiverged
from mpi_cuda_process_tpu.resilience import faults
eng = serving.ServingEngine(telemetry_dir='/tmp/_t1_serving',
                            ladder=(2,), cadence=8)
url = eng.serve(0).url
a_cfg = dict(stencil='heat2d', grid=(32, 32), iters=4096)
victim = eng.submit(RunConfig(seed=1, **a_cfg), tenant='alice')
mate = eng.submit(RunConfig(seed=2, **a_cfg), tenant='bob')
other = eng.submit(RunConfig(stencil='heat2d', grid=(32, 48), iters=8,
                             seed=3), tenant='carol')
seen = {}
def scrape():
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and 'busy' not in seen:
        try:
            s = json.load(urllib.request.urlopen(url + '/status.json',
                                                 timeout=5))
            sch = s.get('scheduler')
            if sch and sch.get('slots_busy', 0) >= 1:
                seen['busy'] = sch
        except OSError:
            pass
        time.sleep(0.05)
t = threading.Thread(target=scrape); t.start()
got_mate, _ = mate.result(timeout=240)
other.result(timeout=240)
try:
    victim.result(timeout=240)
    raise AssertionError('poisoned slot must raise SimulationDiverged')
except SimulationDiverged:
    pass
t.join()
assert 'busy' in seen, 'never scraped a live scheduler block'
assert victim._phase() == 'evicted' and \
    victim.health_verdict() == 'DIVERGED', victim._phase()
stats = eng.close()
assert stats['jobs_done'] == 2 and stats['jobs_evicted'] == 1, stats
assert len(stats['class_table']) == 2, stats['class_table']
assert stats['ttfc_p50_s'] is not None
# the poisoned slot's co-tenant stayed bit-exact: replay it solo with
# the (already consumed) one-shot fault disarmed
os.environ.pop('FAULT_INJECT'); faults.reset()
want, _ = cli.run(RunConfig(seed=2, **a_cfg))
assert all(np.array_equal(np.asarray(a), np.asarray(b))
           for a, b in zip(got_mate, want)), 'co-tenant not bit-exact'
print('serving smoke ok: evicted@%d, %d done, sched busy=%s' % (
    victim.steps_done, stats['jobs_done'], seen['busy']['slots_busy']))
" || rc=1
timeout -k 10 120 python scripts/obs_report.py \
  /tmp/_t1_serving/serving-*.jsonl --check > /dev/null || rc=1
# Elastic-policy smoke (round 19): measurement-driven --auto-policy +
# live no-gather mesh migration end to end.  The run launches on the
# ledger's measured winner (8,1,1); POLICY_INJECT flips the measured
# winner to (1,1,8) at step 20; the recheck must adopt it at that
# chunk boundary (a 'migrate' event with a nonzero collective round
# count — reshard.py, never a host gather) and the final fields must
# bit-match an UNINTERRUPTED run under the target mesh.
rm -rf /tmp/_t1_policy
mkdir -p /tmp/_t1_policy
timeout -k 10 300 python -c "
import dataclasses, json, os, time
import numpy as np
from cpuforce import force_cpu; force_cpu(8)
os.environ['OBS_LEDGER_PATH'] = '/tmp/_t1_policy/ledger.jsonl'
from mpi_cuda_process_tpu import cli
from mpi_cuda_process_tpu.config import RunConfig
from mpi_cuda_process_tpu.obs import ledger
from mpi_cuda_process_tpu.policy import select as ps
base = RunConfig(stencil='heat3d', grid=(16, 16, 16), iters=40,
                 log_every=10)
def row(mesh, value, path, source):
    c = dataclasses.replace(base, mesh=mesh)
    label, _ = ps._ledger_identity(c, 'cpu')
    ledger.append_rows([ledger.make_row(
        label, value, source=source, measured_at=time.time(),
        backend='cpu', flags=ledger._flags(dataclasses.asdict(c)))], path)
row((8, 1, 1), 500.0, '/tmp/_t1_policy/ledger.jsonl', 'seed')
row((1, 1, 8), 900.0, '/tmp/_t1_policy/inject.jsonl', 'inject')
os.environ['POLICY_INJECT'] = 'step=20:/tmp/_t1_policy/inject.jsonl'
tel = '/tmp/_t1_policy/run.jsonl'
fields, _ = cli.run(dataclasses.replace(base, auto_policy=True,
                                        policy_recheck=1, telemetry=tel))
evs = [json.loads(l) for l in open(tel) if l.strip()]
pol = [e for e in evs if e['kind'] == 'policy']
assert pol and pol[0]['provenance'] == 'measured' \
    and pol[0]['decision']['mesh'] == [8, 1, 1], pol
mig = [e for e in evs if e['kind'] == 'migrate']
assert len(mig) == 1 and mig[0]['step'] == 20 \
    and mig[0]['dst']['mesh'] == [1, 1, 8] and mig[0]['rounds'] > 0, mig
os.environ.pop('POLICY_INJECT')
want, _ = cli.run(dataclasses.replace(base, mesh=(1, 1, 8)))
assert all(np.array_equal(np.asarray(a), np.asarray(b))
           for a, b in zip(fields, want)), 'migrated run not bit-exact'
print('policy smoke ok: launched (8,1,1) [measured], migrated @%d to'
      ' (1,1,8) in %d rounds, bit-exact' % (mig[0]['step'],
                                            mig[0]['rounds']))
" || rc=1
timeout -k 10 120 python scripts/obs_report.py /tmp/_t1_policy/run.jsonl \
  --check > /dev/null || rc=1
# The stale-policy detector (perf_gate --policy-check): the injected
# row moved the ledger AFTER the recorded decision, so the replay must
# exit nonzero; --dry reports the same mismatch but forces 0.
if timeout -k 10 120 python scripts/perf_gate.py /tmp/_t1_policy/run.jsonl \
     --policy-check --ledger /tmp/_t1_policy/ledger.jsonl > /dev/null; then
  echo 'perf_gate --policy-check must exit nonzero on a moved ledger' >&2
  rc=1
fi
timeout -k 10 120 python scripts/perf_gate.py /tmp/_t1_policy/run.jsonl \
  --policy-check --dry --ledger /tmp/_t1_policy/ledger.jsonl \
  > /dev/null || rc=1
# Kernel-variant autotune smoke (round 20, ISSUE 16): the measured
# constant sweep end to end on CPU — maybe_autotune probes a 2-variant
# stream sweep (plus the default) on a tiny grid and lands the rows
# under |var:<id> baseline keys; a seeded dominating row makes a
# variant the measured winner, which --auto-policy must resolve into
# the manifest 'policy' event (and the run must then execute that
# variant's kernel, bit-exact by the default-tier tests); an injected
# ledger flip to the OTHER variant must trip perf_gate --policy-check
# (the variant id rides the cli label, so label equality detects the
# moved winner), with --dry reporting the same mismatch at exit 0.
rm -rf /tmp/_t1_tune
mkdir -p /tmp/_t1_tune
timeout -k 10 600 python -c "
import dataclasses, json, os, time
from cpuforce import force_cpu; force_cpu(2)
os.environ['OBS_LEDGER_PATH'] = '/tmp/_t1_tune/ledger.jsonl'
from mpi_cuda_process_tpu import cli
from mpi_cuda_process_tpu.config import RunConfig
from mpi_cuda_process_tpu.obs import ledger
from mpi_cuda_process_tpu.policy import autotune
from mpi_cuda_process_tpu.policy import select as ps
base = RunConfig(stencil='heat3d', grid=(96, 32, 128), iters=4,
                 mesh=(2, 1, 1), fuse=2, fuse_kind='stream')
summary = autotune.maybe_autotune(base, probe_calls=1,
                                  ids=['bz16y16', 'bz8y8'])
assert [s['id'] for s in summary['swept']] \
    == ['default', 'bz16y16', 'bz8y8'], summary
rows = ledger.read_rows('/tmp/_t1_tune/ledger.jsonl')
varkeys = {ledger.baseline_key(r) for r in rows
           if '|var:' in ledger.baseline_key(r)}
assert len(varkeys) == 2, varkeys
def seed(vid, value, path):
    c = dataclasses.replace(base, kernel_variant=vid)
    label, _ = ps._ledger_identity(c, 'cpu')
    ledger.append_rows([ledger.make_row(
        label, value, source='seed', measured_at=time.time(),
        backend='cpu', flags=ledger._flags(dataclasses.asdict(c)))],
        path)
seed('', 1e6, '/tmp/_t1_tune/ledger.jsonl')
seed('bz8y8', 9e6, '/tmp/_t1_tune/ledger.jsonl')
tel = '/tmp/_t1_tune/run.jsonl'
fields, _ = cli.run(dataclasses.replace(base, auto_policy=True,
                                        telemetry=tel))
evs = [json.loads(l) for l in open(tel) if l.strip()]
pol = [e for e in evs if e['kind'] == 'policy']
assert pol and pol[-1]['provenance'] == 'measured' \
    and pol[-1]['decision']['kernel_variant'] == 'bz8y8', pol
seed('bz16y16', 2e7, '/tmp/_t1_tune/ledger.jsonl')
print('autotune smoke ok: swept default+2 variants, |var: keys in the'
      ' ledger, --auto-policy resolved measured winner bz8y8')
" || rc=1
timeout -k 10 120 python scripts/obs_report.py /tmp/_t1_tune/run.jsonl \
  --check > /dev/null || rc=1
# The injected bz16y16 row moved the winning VARIANT after the
# recorded decision: the replay must exit nonzero; --dry forces 0.
if timeout -k 10 120 python scripts/perf_gate.py /tmp/_t1_tune/run.jsonl \
     --policy-check --ledger /tmp/_t1_tune/ledger.jsonl > /dev/null; then
  echo 'perf_gate --policy-check must exit nonzero on a variant flip' >&2
  rc=1
fi
timeout -k 10 120 python scripts/perf_gate.py /tmp/_t1_tune/run.jsonl \
  --policy-check --dry --ledger /tmp/_t1_tune/ledger.jsonl \
  > /dev/null || rc=1
# Fleet-router smoke (round 21, ISSUE 17): three in-process engine
# replicas behind one ServingRouter — mixed size classes warm two
# replicas, a replica is killed mid-stream under a long-running job,
# the job rebalances to a survivor bit-exact vs its solo replay, the
# supervised restart brings the replica back, and the aggregate
# /status.json (schema-validated manifests, one fleet row per replica)
# renders through the obs_top fleet panel with a healthy exit code.
rm -rf /tmp/_t1_router
timeout -k 10 300 python -c "
import json, sys, time, urllib.request
import numpy as np
from cpuforce import force_cpu; force_cpu(8)
from mpi_cuda_process_tpu import cli
from mpi_cuda_process_tpu.config import RunConfig
from mpi_cuda_process_tpu.obs import trace as trace_lib
from mpi_cuda_process_tpu.serving import ServingRouter
r = ServingRouter(replicas=3, ladder=(1, 2), cadence=8,
                  restart_backoff=0.05,
                  telemetry_dir='/tmp/_t1_router')
url = r.serve(0).url
warm = [r.submit(RunConfig(stencil='heat2d', grid=(16, 16 + 8 * (i % 2)),
                           iters=16, seed=i)) for i in range(4)]
for h in warm: h.result(timeout=240)
victim_cfg = RunConfig(stencil='heat2d', grid=(16, 16), iters=60000,
                       seed=9)
victim = r.submit(victim_cfg)
target = victim.replica
while not victim.done() and \\
        victim._inner.timings.get('time_to_first_chunk_s') is None:
    time.sleep(0.01)
assert not victim.done(), 'victim finished before the kill'
assert r.kill_replica(target)
fields, _ = victim.result(timeout=600)
assert victim.resubmits >= 1 and victim.replica != target
want, _ = cli.run(victim_cfg)
assert all(np.array_equal(np.asarray(a), np.asarray(b))
           for a, b in zip(fields, want)), 'rebalanced rerun not bit-exact'
deadline = time.time() + 20
while time.time() < deadline and not r.replicas()[target]['alive']:
    time.sleep(0.05)
assert r.replicas()[target]['alive'], 'supervised restart never landed'
after = r.submit(RunConfig(stencil='heat2d', grid=(16, 16), iters=16,
                           seed=10))
after.result(timeout=240)
for rep in r.replicas().values():
    m = json.loads(open(rep['telemetry']).readline())
    trace_lib.validate_manifest(m)
    assert m['replica'] in ('r0', 'r1', 'r2'), m
time.sleep(0.8)
stat = json.load(urllib.request.urlopen(url + '/status.json', timeout=5))
rows = [row for row in stat.get('hosts', []) if row.get('replica')]
assert len(rows) >= 3, [row.get('key') for row in rows]
assert stat.get('router', {}).get('counts', {}).get('replica_dead') == 1
# the live fleet page renders through the obs_top fleet panel with a
# healthy exit code AFTER the recovery
sys.path.insert(0, 'scripts')
import obs_top
body, status = obs_top.frame(url, None)
assert obs_top.health_rc(status) == 0, 'fleet unhealthy after recovery'
assert 'router' in body and 'replica' in body, body
stats = r.close()
assert stats['lost_jobs'] == 0 and stats['jobs_done'] == 6, stats
assert stats['rebalanced'] >= 1 and stats['restarts'] == 1, stats
assert stats['ttfc_p50_s'] is not None
print('router smoke ok: kill->rebalance->restart, %d done, 0 lost, '
      '%d fleet rows' % (stats['jobs_done'], len(rows)))
" || rc=1
timeout -k 10 120 python scripts/obs_top.py /tmp/_t1_router/router-*.jsonl \
  --once > /dev/null || rc=1
# Coupled device-group smoke (round 22, ISSUE 18): the MPMD engine end
# to end on CPU — a 2-group heterogeneous run (fine wave3d + coarse
# heat3d, coupled only at the interface faces) through the ordinary CLI
# path, with (1) the jaxpr isolation gate (zero collectives in the
# cross-group transfers, intra-group ppermutes only where a sub-mesh
# shards), (2) per-group chunk telemetry + the resolved groups block in
# a schema-valid manifest, and (3) the status payload carrying one row
# per group.  The bit-exactness of same-physics splits is pinned by the
# default-tier tests (tests/test_groups.py); this smoke pins the
# end-to-end coupled loop every build.
rm -f /tmp/_t1_groups.jsonl
timeout -k 10 300 python -c "
import json
from cpuforce import force_cpu; force_cpu(8)
from mpi_cuda_process_tpu import cli
from mpi_cuda_process_tpu.obs.metrics import RunMetrics
from mpi_cuda_process_tpu.utils import jaxprcheck
gspec = 'wave3d:fine@0-3:z1/4:mesh1x4,heat3d:coarse@4-7:mesh1x4'
rep = jaxprcheck.check_coupled_structure()  # 2-group same-physics gate
assert rep['groups'] == ['g0:heat3d', 'g1:heat3d'], rep
fields, mcells = cli.run(cli.config_from_args(
    ['--stencil', 'wave3d', '--grid', '24,16,16', '--iters', '8',
     '--groups', gspec, '--log-every', '2', '--health',
     '--telemetry', '/tmp/_t1_groups.jsonl']))
assert fields[0].shape == (24, 16, 16) and mcells > 0
recs = [json.loads(l) for l in open('/tmp/_t1_groups.jsonl')
        if l.strip()]
rm = RunMetrics()
for r in recs:
    rm.ingest(r)
man = next(r for r in recs if r.get('kind') == 'manifest')
assert [g['group'] for g in man['groups']] \
    == ['g0:wave3d', 'g1:heat3d'], man.get('groups')
gc = {r['group'] for r in recs if r.get('kind') == 'group_chunk'}
assert gc == {'g0:wave3d', 'g1:heat3d'}, gc
st = rm.status()
grp = st['groups']
assert grp['n_groups'] == 2 and len(grp['rows']) == 2, grp
assert grp['worst_verdict'] == 'HEALTHY', grp
fin = next(r for r in recs if r.get('kind') == 'summary')
assert fin['coupled'] is True and fin['n_groups'] == 2, fin
print('groups smoke ok: 2 groups coupled, %.4f Mcells/s, rows=%s'
      % (mcells, [r['group'] for r in grp['rows']]))
" || rc=1
timeout -k 10 120 python scripts/obs_report.py /tmp/_t1_groups.jsonl \
  --check > /dev/null || rc=1
# Collective interface-transport smoke (round 23, ISSUE 19): the same
# heterogeneous coupled run under --group-transport collective — the
# interface bands ride ppermute rounds over the union device set, zero
# host hops.  Pins (1) the transport jaxpr gate (no device_put anywhere,
# exactly 2*interfaces ppermutes, nothing else collective), (2) the
# manifest groups block carrying per-group transport + mode tokens,
# (3) the costmodel<->budget pricing cross-check (bytes_per_round ==
# the itemized per-direction budget parts, both transports), and (4) a
# schema-valid log (obs_report --check below).
rm -f /tmp/_t1_grpcoll.jsonl
timeout -k 10 300 python -c "
import json
from cpuforce import force_cpu; force_cpu(8)
from mpi_cuda_process_tpu import cli
from mpi_cuda_process_tpu.obs import costmodel
from mpi_cuda_process_tpu.parallel import groups as groups_lib
from mpi_cuda_process_tpu.utils import budget, jaxprcheck
gspec = 'wave3d:fine@0-3:z1/4:mesh1x4:overlap,heat3d:coarse@4-7:mesh1x4'
rep = jaxprcheck.check_group_transport_structure(gspec, (24, 16, 16))
assert rep['transport'] == 'collective', rep
assert rep['n_ppermute'] == 2 and rep['n_device_put'] == 0, rep
fields, mcells = cli.run(cli.config_from_args(
    ['--stencil', 'wave3d', '--grid', '24,16,16', '--iters', '8',
     '--groups', gspec, '--group-transport', 'collective',
     '--log-every', '2', '--telemetry', '/tmp/_t1_grpcoll.jsonl']))
assert fields[0].shape == (24, 16, 16) and mcells > 0
recs = [json.loads(l) for l in open('/tmp/_t1_grpcoll.jsonl')
        if l.strip()]
man = next(r for r in recs if r.get('kind') == 'manifest')
assert man['run'].get('group_transport') == 'collective', man['run']
gb = man['groups']
assert [g['transport'] for g in gb] == ['collective'] * 2, gb
assert gb[0]['modes'] == ['overlap'] and gb[1]['modes'] == [], gb
assert all('clause' in g for g in gb), gb
plans = groups_lib.plans_from_config(gspec, (24, 16, 16), n_devices=8)
for t in ('collective', 'device_put'):
    c = costmodel.coupled_cost(plans, 1.2e12, 4.5e10, transport=t)['interface']
    assert c['transport'] == t, c
    _, per_group = budget.estimate_coupled_bytes(plans, transport=t)
    parts = [p for _, _, ps in per_group for p in ps]
    staged = sum(b for n, b in parts if 'raw staged rows' in n
                 or 'staged send' in n)
    assert staged == c['staged_bytes_per_round'], (t, staged, c)
    wire = sum(b for n, b in parts if 'collective wire chunk' in n
               or ('staged send' in n and t == 'device_put'))
    recv = sum(b for n, b in parts if 'band recv' in n)
    want = wire if t == 'collective' else recv
    assert c['bytes_per_round'] == want, (t, c['bytes_per_round'], want)
print('collective groups smoke ok: %d ppermutes, 0 device_put, '
      '%.4f Mcells/s' % (rep['n_ppermute'], mcells))
" || rc=1
timeout -k 10 120 python scripts/obs_report.py /tmp/_t1_grpcoll.jsonl \
  --check > /dev/null || rc=1
# Run-doctor smoke (round 24, ISSUE 20): the performance-anomaly E2E
# pin.  Two injected sleep faults (the 'sleep:MS' action stalls the
# chunk boundary OUTSIDE the fenced device window — exactly where real
# boundary trouble lands) under --anomaly --serve 0 must (1) flag a
# boundary_stall within 2 chunk boundaries of the first stall with the
# host named as suspect, (2) flip /status.json to DEGRADED, scraped
# LIVE during the second injected stall (an 800 ms window the 20 Hz
# poller cannot miss), (3) finish the run anyway (DEGRADED warns, never
# kills — a slow run is not a dead run) with the ledger row flagged
# degraded=N but NOT quarantined, and (4) leave the flight-recorder
# bundle next to the log, self-validating via obs_report --check.
# obs_top --once on the log must exit nonzero (the DEGRADED CI-probe
# contract, same as WEDGED/DIVERGED).
rm -rf /tmp/_t1_doctor
mkdir -p /tmp/_t1_doctor
timeout -k 10 300 env \
  FAULT_INJECT='exchange:step=8:sleep:500,exchange:step=12:sleep:800' \
  python -c "
import json, threading, time, urllib.request
from cpuforce import force_cpu; force_cpu()
from mpi_cuda_process_tpu import cli
from mpi_cuda_process_tpu.obs import ledger
tel = '/tmp/_t1_doctor/run.jsonl'
seen = {}
def scrape():
    url = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and url is None:
        try:
            for line in open(tel):
                rec = json.loads(line)
                if rec.get('kind') == 'serve':
                    url = rec['url']
        except (OSError, ValueError):
            pass
        if url is None:
            time.sleep(0.05)
    while time.monotonic() < deadline and 'degraded' not in seen:
        try:
            s = json.load(urllib.request.urlopen(url + '/status.json',
                                                 timeout=5))
            if s.get('verdict') == 'DEGRADED':
                seen['degraded'] = s
        except OSError:
            pass
        time.sleep(0.05)
t = threading.Thread(target=scrape); t.start()
cli.run(cli.config_from_args(
    ['--stencil', 'heat2d', '--grid', '16,64', '--iters', '24',
     '--log-every', '2', '--anomaly', '--serve', '0',
     '--telemetry', tel]))
t.join()
s = seen.get('degraded')
assert s is not None, 'never scraped a live DEGRADED /status.json'
an = s.get('anomalies') or {}
assert an.get('count', 0) >= 1, an
assert (an.get('suspect') or {}).get('name'), an
evs = [json.loads(line) for line in open(tel) if line.strip()]
anoms = [e for e in evs if e.get('kind') == 'anomaly']
assert anoms and anoms[0]['anomaly'] == 'boundary_stall', anoms[:1]
# flagged within 2 chunk boundaries of the step-8 stall
assert anoms[0].get('step', 99) <= 12, anoms[0]
assert any(e.get('kind') == 'summary' for e in evs), 'run must finish'
rows = [r for r in ledger.rows_from_log(tel) if r.get('value')]
assert rows and rows[0]['status'] == 'ok', rows
assert rows[0]['detail']['degraded'] == len(anoms), rows[0]
import os
assert os.path.exists('/tmp/_t1_doctor/run.bundle.json'), 'no bundle'
print('doctor smoke ok: %d finding(s), suspect %s, DEGRADED live,'
      ' ledger degraded=%d, bundle on exit' % (
          len(anoms), an['suspect']['name'],
          rows[0]['detail']['degraded']))
" || rc=1
timeout -k 10 120 python scripts/obs_report.py \
  /tmp/_t1_doctor/run.bundle.json --check > /dev/null || rc=1
if timeout -k 10 120 python scripts/obs_top.py /tmp/_t1_doctor/run.jsonl \
     --once > /dev/null; then
  echo 'obs_top --once must exit nonzero on a DEGRADED log' >&2; rc=1
fi
# Flight-recorder give-up smoke: a wedged child (exchange hang) under a
# no-restart supervisor must leave the post-mortem bundle — the
# supervisor's own ring plus the SIGKILLed child's log tail — and the
# bundle must render standalone AFTER the telemetry directory is
# deleted (the whole point of a flight recorder: the evidence survives
# the crash site).
rm -rf /tmp/_t1_flight
timeout -k 10 240 env FAULT_INJECT='exchange:step=40:hang' \
  FAULT_HANG_S=120 python -c "
import json
from cpuforce import force_cpu; force_cpu()
from mpi_cuda_process_tpu.config import RunConfig
from mpi_cuda_process_tpu.resilience import supervisor as sup
rc = sup.run_supervised(RunConfig(
    stencil='life', grid=(64, 64), iters=100, seed=7,
    checkpoint_every=10, checkpoint_dir='/tmp/_t1_flight/ck',
    telemetry='/tmp/_t1_flight/run.jsonl', supervise=True,
    max_restarts=0, restart_backoff=0.3, supervise_stall_s=8.0))
assert rc == 1, f'supervisor rc={rc} (want give-up)'
evs = [json.loads(l)
       for l in open('/tmp/_t1_flight/run.supervisor.jsonl') if l.strip()]
gu = [e for e in evs if e.get('kind') == 'give_up']
assert gu, [e.get('kind') for e in evs]
bun = [e for e in evs if e.get('kind') == 'bundle']
assert bun and bun[0].get('path'), 'give-up must record its bundle'
print('BUNDLE_PATH=' + bun[0]['path'])
" | tee /tmp/_t1_flight_out.txt || rc=1
bundle_path=$(grep -a '^BUNDLE_PATH=' /tmp/_t1_flight_out.txt | cut -d= -f2)
if [ -n "$bundle_path" ] && [ -f "$bundle_path" ]; then
  cp "$bundle_path" /tmp/_t1_flight.bundle.json
  rm -rf /tmp/_t1_flight   # the crash site is gone; the bundle survives
  timeout -k 10 120 python scripts/obs_report.py \
    /tmp/_t1_flight.bundle.json --check > /dev/null || rc=1
else
  echo 'give-up flight bundle missing' >&2; rc=1
fi
# The committed campaign ledger must render in both one-command
# summary surfaces: obs_report --ledger (best_known + quarantine
# table) and the terminal monitor's ledger mode.
timeout -k 10 120 python scripts/obs_report.py --ledger > /dev/null || rc=1
timeout -k 10 120 python scripts/obs_top.py benchmarks/ledger.jsonl \
  --once > /dev/null || rc=1
# Ledger + perf-gate smoke against a throwaway ledger: backfill the
# historical BENCH_r0*/results_r0* files (quarantine rules exercised on
# the real wedge rounds), ingest the smoke manifest, and run the gate in
# --dry mode — the full measurement->ledger->gate loop every build.
timeout -k 10 120 python scripts/perf_gate.py --backfill \
  --ledger /tmp/_t1_ledger.jsonl > /dev/null || rc=1
timeout -k 10 120 python scripts/perf_gate.py /tmp/_t1_obs.jsonl --dry \
  --update-ledger --ledger /tmp/_t1_ledger.jsonl || rc=1
exit $rc
