#!/usr/bin/env python3
"""On-demand flight-recorder bundle from a telemetry log.

    python scripts/obs_bundle.py PATH.jsonl [-o OUT] [--no-tunnel]

Reads one telemetry JSONL log and writes the self-contained post-mortem
bundle (``obs/flightrec.py``) next to it — manifest, last-N events,
anomaly findings, replayed verdict, ledger ``best_known`` for the
label, ``diagnose_tunnel`` verdict, env snapshot.  The bundle is what
you hand to a fresh session (or attach to a round report) when the
telemetry dir itself won't survive: ``scripts/obs_report.py BUNDLE``
renders it, ``--check`` validates it.

The probe ladder (``diagnose_tunnel``) runs by default here — an
on-demand post-mortem is exactly when you want the tunnel verdict —
and is skippable with ``--no-tunnel`` (or ``OBS_BUNDLE_TUNNEL=0`` for
the in-run emission paths, where it defaults off).
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_process_tpu.obs import flightrec  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="emit a self-contained flight-recorder bundle "
                    "from a telemetry log")
    p.add_argument("path", help="telemetry JSONL log")
    p.add_argument("-o", "--out", default=None,
                   help="bundle path (default: <log>.bundle.json)")
    p.add_argument("--no-tunnel", action="store_true",
                   help="skip the diagnose_tunnel probe ladder")
    p.add_argument("--reason", default="on-demand",
                   help="reason recorded in the bundle")
    a = p.parse_args(argv)
    try:
        out = flightrec.bundle_from_log(
            a.path, reason=a.reason,
            run_tunnel=False if a.no_tunnel else True,
            out_path=a.out)
    except (OSError, ValueError) as e:
        print(f"obs_bundle: {e}", file=sys.stderr)
        return 2
    b = flightrec.read_bundle(out)
    print(f"wrote {out}")
    print(f"  verdict={b['verdict']} events={len(b['events'])} "
          f"anomalies={len(b['anomalies'])} "
          f"tunnel={b['tunnel']['verdict']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
