#!/usr/bin/env python
"""Perf regression gate: a fresh telemetry manifest vs the ledger baseline.

Reads one telemetry JSONL (any of the four obs/ tools), derives its
measurement rows (``obs/ledger.rows_from_log``), and compares each
against the best-known baseline for the same label on the same backend
(``obs/ledger.best_known`` — quarantined rows are structurally excluded,
so a stale/0.0/wedged record can never be the number a run is judged
against).  Verdicts, with a configurable relative noise band
(``--noise``, default 10%):

    IMPROVED      fresh >= baseline * (1 + noise)
    OK            fresh >  baseline * (1 - noise)
    REGRESSED     fresh <= baseline * (1 - noise)
    NO_BASELINE   no ok ledger row for this label x backend
    QUARANTINED   the fresh row itself failed quarantine (0.0, stale,
                  suspect, backend mismatch, wedged heartbeat) — it is
                  neither scored nor ever a baseline

A row whose value was measured after a supervised restart/resume
(``resilience/supervisor.py`` — the detail carries ``attempts`` /
``restart_attempts`` / ``resumed_from_step``) is judged normally but
FLAGGED ``[after-restart]`` in the table and counted in the summary:
the value is honest (resume is bit-exact), the wall-clock path that
produced it was not uninterrupted.  A row whose run carried run-doctor
anomaly findings (``--anomaly``, detail ``degraded=N``) gets the same
treatment: judged normally, FLAGGED ``[degraded]``, counted in the
summary — a slow run is not a dead run, but the number deserves its
asterisk.

Exit status: 0 clean, 1 when any row REGRESSED (CI-gate mode), 2 on
usage/IO errors.  ``--dry`` always exits 0 (the tier-1 smoke mode —
the table still prints).  ``--update-ledger`` appends the fresh rows
(ok AND quarantined, idempotently) after the verdicts are computed, so
one invocation both gates a round and makes it the next round's
baseline; ``--backfill`` runs the one-shot historical ingest
(BENCH_r0*.json + benchmarks/results_r0*.json) instead of gating.

``--policy-check`` is the stale-policy detector: instead of gating
values, it replays the manifest's recorded ``policy`` event (an
``--auto-policy`` run records the chosen config, its provenance, the
locked overrides, and the device count) against the CURRENT ledger —
same requested config, same locked set, same backend and device
budget — and exits 1 when today's winner differs from the recorded
decision.  A clean exit means the decision that run shipped with is
still what ``--auto-policy`` would pick; a mismatch means the ledger
has learned something since (re-run, or expect a mid-flight migration
under ``--policy-recheck``).  A manifest with no ``policy`` event
passes vacuously (noted in the output).

Safe on a wedged box: the CPU backend is forced before the package
(and hence any jax backend) loads; the ledger itself never touches a
device.

Usage:
    python scripts/perf_gate.py RUN.jsonl [--ledger PATH] [--noise F]
                                [--dry] [--update-ledger]
    python scripts/perf_gate.py RUN.jsonl --policy-check [--ledger PATH]
    python scripts/perf_gate.py --backfill [--ledger PATH]
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from cpuforce import force_cpu  # noqa: E402

force_cpu()  # before the package (and hence any jax backend) loads

from mpi_cuda_process_tpu.obs import ledger as ledger_lib  # noqa: E402

VERDICT_ORDER = ("REGRESSED", "QUARANTINED", "NO_BASELINE", "OK",
                 "IMPROVED")


def judge(fresh_row, baseline_row, noise: float):
    """One row's verdict: ``(verdict, ratio_or_None)``."""
    if fresh_row.get("status") != "ok":
        return "QUARANTINED", None
    if baseline_row is None:
        return "NO_BASELINE", None
    ratio = float(fresh_row["value"]) / float(baseline_row["value"])
    if ratio >= 1.0 + noise:
        return "IMPROVED", ratio
    if ratio > 1.0 - noise:
        return "OK", ratio
    return "REGRESSED", ratio


def gate(manifest_path: str, ledger_path: str, noise: float):
    """Verdict rows for one manifest: list of dicts, one per label."""
    fresh = ledger_lib.rows_from_log(manifest_path)
    source = f"telemetry:{os.path.abspath(manifest_path)}"
    # the same log may already be in the ledger (the tools auto-ingest);
    # a run must never be its own baseline
    history = [r for r in ledger_lib.read_rows(ledger_path)
               if r["source"] != source]
    baselines = ledger_lib.best_known(history)
    out = []
    for row in fresh:
        base = baselines.get(ledger_lib.baseline_key(row))
        verdict, ratio = judge(row, base, noise)
        det = row.get("detail") or {}
        # A value measured after a supervised restart/resume is HONEST
        # (the resumed run bit-matches an uninterrupted one — the
        # checkpoint contract) but flagged: the wall-clock path that
        # produced it included a kill+relaunch, so a surprising number
        # deserves the extra context before anyone chases it.
        restarted = bool(det.get("attempts", 0) and det["attempts"] > 1) \
            or bool(det.get("restart_attempts")) \
            or det.get("resumed_from_step") is not None
        # Same discipline for the run doctor (--anomaly): a value from
        # a run that carried anomaly findings is honest — the steps ran
        # and the numbers are real — but DEGRADED, so the row is
        # flagged rather than quarantined.
        degraded = det.get("degraded")
        degraded = int(degraded) if isinstance(degraded, int) else 0
        out.append({
            "label": row["label"],
            "backend": row["key"].get("backend"),
            "verdict": verdict,
            "value": row.get("value"),
            "unit": row.get("unit"),
            "baseline": base["value"] if base else None,
            "ratio": round(ratio, 4) if ratio is not None else None,
            "quarantine": row.get("quarantine"),
            "restarted": restarted,
            "degraded": degraded,
            "baseline_source": base["source"] if base else None,
            "baseline_measured_at": base.get("measured_at")
            if base else None,
        })
    return out, fresh


def policy_check(manifest_path: str, ledger_path: str) -> int:
    """Replay a manifest's recorded policy decision against the
    current ledger.  Returns the exit code (0 current, 1 stale).

    The manifest's ``run`` dict is the RESOLVED config (the decision
    already applied), so the launch-time question is reconstructed
    from the policy event itself: ``requested`` mode fields overlaid
    on the run dict, re-resolved with the recorded locked set, backend
    and device budget.  Everything that matters is replayed from the
    record — the check is deterministic on any box, including one with
    a different device count than the run had.
    """
    import json

    manifest = None
    event = None
    with open(manifest_path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if manifest is None and rec.get("kind") == "manifest":
                manifest = rec
            if rec.get("kind") == "policy":
                event = rec  # last wins: a retried run re-records
    if manifest is None:
        raise ValueError("no manifest record in the log")
    if event is None:
        print(f"perf_gate --policy-check: {manifest_path} has no "
              "policy event (not an --auto-policy run) — nothing to "
              "check")
        return 0

    from mpi_cuda_process_tpu.config import RunConfig  # noqa: E402
    from mpi_cuda_process_tpu.policy import select as policy_select  # noqa: E402

    requested = {
        k: tuple(v) if isinstance(v, list) else v
        for k, v in (event.get("requested") or {}).items()
        if k in policy_select.MODE_FIELDS}
    run = dict(manifest.get("run") or {})
    if event.get("requested_groups"):
        # coupled (round 23): the run dict carries the RESOLVED groups
        # spec (per-group mode tokens already applied) — restore the
        # launch-time question so the per-group resolution replays
        run["groups"] = event["requested_groups"]
    cfg = RunConfig.from_dict({**run, **requested})
    fresh = policy_select.resolve(
        cfg,
        backend=event.get("backend"),
        ledger_path=ledger_path,
        locked=frozenset(event.get("overrides") or {}),
        n_devices=event.get("n_devices"))

    recorded_label = event.get("label")
    print(f"perf_gate --policy-check: {manifest_path} vs {ledger_path}")
    print(f"  recorded: {recorded_label}  "
          f"[{event.get('provenance')}"
          + (f", {event['value']:g} {event.get('unit', '')}".rstrip()
             if event.get("value") is not None else "") + "]")
    print(f"  current:  {fresh.label}  [{fresh.provenance}"
          + (f", {fresh.value:g} {fresh.unit}"
             if fresh.value is not None else "") + "]")
    stale = fresh.label != recorded_label
    if event.get("groups") is not None:
        # a coupled winner can move WITHOUT moving the run label (mode
        # tokens do not change it — only the |grp: signature): compare
        # the resolved canonical spec, and name the group that moved
        rec_groups = {d.get("group"): d for d in
                      event.get("group_decisions") or []}
        for d in fresh.group_decisions:
            rec = rec_groups.get(d["group"]) or {}
            moved = rec.get("clause") != d["clause"]
            print(f"  group {d['group']}: recorded "
                  f"{rec.get('clause')!r} [{rec.get('provenance')}] "
                  f"-> current {d['clause']!r} [{d['provenance']}]"
                  + ("  <-- MOVED" if moved else ""))
            stale = stale or moved
        stale = stale or fresh.groups != event["groups"]
    if not stale:
        print("policy-check: OK — the recorded decision is still the "
              "ledger winner")
        return 0
    print("policy-check: STALE — the ledger has moved since this run's "
          "decision was made", file=sys.stderr)
    for row in fresh.table[:4]:
        print(f"    {row['provenance']:<9} {row['value']:>10g}  "
              f"{row['label']}")
    return 1


def _table(rows):
    header = ["label", "verdict", "fresh", "baseline", "ratio", "why/src"]
    body = []
    for r in rows:
        why = r["quarantine"] if r["verdict"] == "QUARANTINED" \
            else (r["baseline_source"] or "")
        if r.get("restarted"):
            why = ("[after-restart] " + (why or "")).strip()
        if r.get("degraded"):
            why = ("[degraded] " + (why or "")).strip()
        body.append([
            r["label"][:58], r["verdict"],
            "-" if r["value"] is None else f"{r['value']:g}",
            "-" if r["baseline"] is None else f"{r['baseline']:g}",
            "-" if r["ratio"] is None else f"{r['ratio']:.3f}",
            (why or "")[:44]])
    widths = [max(len(str(r[i])) for r in [header] + body)
              for i in range(len(header))]
    lines = ["  ".join(str(h).ljust(w) for h, w in zip(header, widths)),
             "  ".join("-" * w for w in widths)]
    lines += ["  ".join(str(c).ljust(w) for c, w in zip(r, widths))
              for r in body]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("manifest", nargs="?",
                    help="fresh telemetry JSONL to gate")
    ap.add_argument("--ledger", default=None,
                    help="ledger path (default: OBS_LEDGER_PATH or "
                         "benchmarks/ledger.jsonl)")
    ap.add_argument("--noise", type=float, default=0.10,
                    help="relative noise band (default 0.10 = +/-10%%)")
    ap.add_argument("--dry", action="store_true",
                    help="print the verdict table but always exit 0 "
                         "(the tier-1 smoke mode)")
    ap.add_argument("--update-ledger", action="store_true",
                    help="append the fresh rows to the ledger after "
                         "gating (idempotent)")
    ap.add_argument("--backfill", action="store_true",
                    help="one-shot historical ingest instead of gating")
    ap.add_argument("--policy-check", action="store_true",
                    help="replay the manifest's recorded policy "
                         "decision against the current ledger instead "
                         "of gating values; exit 1 when the winner "
                         "has moved")
    a = ap.parse_args(argv)
    ledger_path = a.ledger or ledger_lib.default_ledger_path()

    if a.policy_check:
        if not a.manifest:
            ap.error("--policy-check needs a telemetry manifest")
        try:
            rc = policy_check(a.manifest, ledger_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"perf_gate: cannot policy-check {a.manifest}: {e}",
                  file=sys.stderr)
            return 2
        if rc and a.dry:
            print("perf_gate: --dry — stale policy reported, exit "
                  "forced 0")
            return 0
        return rc

    if a.backfill:
        out = ledger_lib.backfill(ledger_path=ledger_path)
        print(f"perf_gate --backfill: {out['found']} rows found, "
              f"{out['appended']} appended "
              f"({out['quarantined']} quarantined) -> {ledger_path}")
        return 0
    if not a.manifest:
        ap.error("need a telemetry manifest to gate (or --backfill)")

    try:
        verdicts, fresh = gate(a.manifest, ledger_path, a.noise)
    except (OSError, ValueError) as e:
        print(f"perf_gate: cannot gate {a.manifest}: {e}",
              file=sys.stderr)
        return 2

    verdicts.sort(key=lambda r: VERDICT_ORDER.index(r["verdict"]))
    counts = {}
    for r in verdicts:
        counts[r["verdict"]] = counts.get(r["verdict"], 0) + 1
    print(f"perf_gate: {a.manifest} vs {ledger_path} "
          f"(noise +/-{a.noise:.0%})")
    print(_table(verdicts) if verdicts else "(no measurement rows in "
                                           "this manifest)")
    restarted = sum(1 for r in verdicts if r.get("restarted"))
    degraded = sum(1 for r in verdicts if r.get("degraded"))
    print("summary: " + "  ".join(
        f"{v}={counts.get(v, 0)}" for v in VERDICT_ORDER)
        + (f"  restarted={restarted}" if restarted else "")
        + (f"  degraded={degraded}" if degraded else ""))

    if a.update_ledger:
        n = ledger_lib.append_rows(fresh, ledger_path)
        print(f"ledger updated: {n} rows appended -> {ledger_path}")

    regressed = counts.get("REGRESSED", 0)
    if regressed and not a.dry:
        print(f"perf_gate: FAIL — {regressed} label(s) regressed past "
              f"the {a.noise:.0%} noise band", file=sys.stderr)
        return 1
    if regressed:
        print(f"perf_gate: --dry — {regressed} regression(s) reported, "
              "exit forced 0")
    return 0


if __name__ == "__main__":
    sys.exit(main())
