#!/usr/bin/env python
"""Export telemetry JSONL log(s) into ONE Chrome-trace/Perfetto JSON.

The post-hoc face of the span layer (``obs/spans.py``): any run — a
single CLI run, a supervised run with restarts (supervisor log + one
log per attempt), or N per-host logs of a multi-host run — renders as
a single causal timeline loadable in ``chrome://tracing`` or
https://ui.perfetto.dev.  Tracks are **hosts/processes** (one Chrome
"process" per schema-2 ``hostname`` x ``process_index``, one "thread"
per source log: ``supervisor``, ``attempt0``, ``attempt1``, ...);
slices are the span vocabulary — ``compile``, ``chunk``,
``checkpoint``, ``kill``, ``backoff``, ``restart``, ``resume``,
``attempt``, ``request`` — drawn from span records where the log has
them and synthesized from ``chunk`` events (``t`` − ``wall_s``)
everywhere, so pre-span logs still export.  Instant markers carry the
point events: heartbeat verdicts, launches, errors, give-up, exchange
mode, policy/``policy_group`` decisions, ``migrate``, (group-named)
``health`` verdicts, and run-doctor ``anomaly`` findings.  Coupled
``--groups`` runs additionally get one synthetic track per device
group built from its ``group_chunk`` events, so heterogeneous physics
renders side by side.

Every exported slice keeps its ``trace_id``/``span_id``/``parent_id``
in ``args``, so "do the supervisor and both attempts share one trace?"
is a one-liner over the output (the tier-1 span smoke asserts exactly
that).  The export is self-validating: :func:`validate_export` runs on
the built object before anything is written, and a schema problem is a
nonzero exit, not a silently broken JSON.

Usage::

    python scripts/obs_trace_export.py PATH [PATH...] [-o OUT.json]

``PATH`` may be a telemetry JSONL file, a directory (every ``*.jsonl``
inside), or a supervised run's base path — ``run.jsonl`` expands to
every ``run.*.jsonl`` sibling (``.supervisor`` + ``.attemptN``), which
is how a supervised run that never wrote the base file itself is named
by one argument.  Safe on a wedged box: no jax import anywhere.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# instant-marker mapping: obs event kind -> slice name builder.
# policy / policy_group / migrate / health / anomaly joined in round 20
# so --groups runs (PR 18/19 vocabulary) and run-doctor findings land
# on the timeline instead of vanishing.
_INSTANT_KINDS = ("heartbeat", "launch", "give_up", "error", "abort",
                  "resume", "exchange", "serve", "summary", "restart",
                  "policy", "policy_group", "migrate", "health",
                  "anomaly")


def discover(arg: str) -> List[str]:
    """Expand one CLI argument into concrete log paths (see module
    docstring).  Order: the file itself, then sorted siblings."""
    if os.path.isdir(arg):
        return sorted(glob.glob(os.path.join(arg, "*.jsonl")))
    out: List[str] = []
    if os.path.exists(arg):
        out.append(arg)
    if arg.endswith(".jsonl"):
        for sib in sorted(glob.glob(arg[:-len(".jsonl")] + ".*.jsonl")):
            if sib not in out:
                out.append(sib)
    return out


def read_records(path: str) -> List[Dict[str, Any]]:
    """Complete, well-formed dict lines only (a SIGKILLed writer's torn
    tail is dropped, same contract as ``trace.LogTail``)."""
    out: List[Dict[str, Any]] = []
    try:
        fh = open(path, "rb")
    except OSError:
        return out
    with fh:
        for line in fh:
            if not line.endswith(b"\n"):
                break
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line.decode("utf-8", errors="replace"))
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _tag(path: str, manifest: Optional[Dict[str, Any]]) -> str:
    """Thread name for one source log: the supervised sibling tag when
    the filename carries one, else the manifest's tool, else the stem."""
    base = os.path.basename(path)
    if base.endswith(".jsonl"):
        base = base[:-len(".jsonl")]
    parts = base.rsplit(".", 1)
    if len(parts) == 2 and parts[1]:
        return parts[1]  # run.supervisor.jsonl -> "supervisor"
    if manifest is not None and isinstance(manifest.get("tool"), str):
        return manifest["tool"]
    return base


def _us(t: float) -> float:
    return round(float(t) * 1e6, 1)


def build_trace(paths: List[str]) -> Dict[str, Any]:
    """Fold every log into one Chrome-trace object (see module doc)."""
    events: List[Dict[str, Any]] = []
    pids: Dict[str, int] = {}  # host|pN -> chrome pid
    trace_ids = set()
    files_read = 0
    for tid_num, path in enumerate(paths, start=1):
        recs = read_records(path)
        if not recs:
            continue
        files_read += 1
        manifest = recs[0] if recs[0].get("kind") == "manifest" else None
        prov = (manifest or {}).get("provenance") or {}
        host = prov.get("hostname") or "?"
        pidx = prov.get("process_index")
        group = f"{host}|p{pidx if isinstance(pidx, int) else '?'}"
        if group not in pids:
            pids[group] = len(pids) + 1
            events.append({
                "name": "process_name", "ph": "M", "pid": pids[group],
                "tid": 0, "args": {"name": f"{host} p{pidx}/"
                                           f"{prov.get('process_count')}"}})
        pid = pids[group]
        thread = _tag(path, manifest)
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid_num, "args": {"name": thread}})
        mtrace = (manifest or {}).get("trace") or {}
        if mtrace.get("trace_id"):
            trace_ids.add(mtrace["trace_id"])
        src = os.path.basename(path)
        # coupled runs (--groups): one synthetic thread per device
        # group under this log's process, so Perfetto shows the groups
        # side by side instead of interleaved on one track
        gtids: Dict[str, int] = {}
        for rec in recs:
            kind = rec.get("kind")
            t = rec.get("t")
            if kind == "span":
                start, dur = rec.get("start"), rec.get("dur_s")
                if not isinstance(start, (int, float)) or \
                        not isinstance(dur, (int, float)):
                    continue
                if rec.get("trace_id"):
                    trace_ids.add(rec["trace_id"])
                args = dict(rec.get("attrs") or {})
                args.update({"trace_id": rec.get("trace_id"),
                             "span_id": rec.get("span_id"),
                             "parent_id": rec.get("parent_id"),
                             "file": src})
                events.append({
                    "name": str(rec.get("name") or "span"), "ph": "X",
                    "cat": "span", "ts": _us(start),
                    "dur": max(1.0, _us(dur)), "pid": pid,
                    "tid": tid_num, "args": args})
            elif kind == "chunk" and isinstance(t, (int, float)):
                wall = rec.get("wall_s")
                if not isinstance(wall, (int, float)) or wall < 0:
                    continue
                n = rec.get("chunk")
                args = {k: rec.get(k) for k in
                        ("chunk", "steps", "ms_per_step", "recompiled",
                         "members") if rec.get(k) is not None}
                args["file"] = src
                events.append({
                    "name": f"chunk {n}", "ph": "X", "cat": "chunk",
                    "ts": _us(t - wall), "dur": max(1.0, _us(wall)),
                    "pid": pid, "tid": tid_num, "args": args})
            elif kind == "group_chunk" and isinstance(t, (int, float)):
                wall = rec.get("wall_s")
                gname = rec.get("group")
                if not isinstance(wall, (int, float)) or wall <= 0 or \
                        not isinstance(gname, str) or not gname:
                    continue
                gt = gtids.get(gname)
                if gt is None:
                    # tids 1..N are source logs; group tracks live in a
                    # disjoint per-log band so they can never collide
                    gt = gtids[gname] = 1000 * tid_num + len(gtids) + 1
                    events.append({"name": "thread_name", "ph": "M",
                                   "pid": pid, "tid": gt,
                                   "args": {"name": f"{thread}:{gname}"}})
                args = {k: rec.get(k) for k in
                        ("group", "op", "ratio", "dtype", "step",
                         "steps", "ready_ms_per_step", "mcells_per_s")
                        if rec.get(k) is not None}
                args["file"] = src
                events.append({
                    "name": f"{gname} chunk@{rec.get('step')}",
                    "ph": "X", "cat": "group_chunk",
                    "ts": _us(t - wall), "dur": max(1.0, _us(wall)),
                    "pid": pid, "tid": gt, "args": args})
            elif kind in _INSTANT_KINDS and isinstance(t, (int, float)):
                name = kind
                if kind == "heartbeat":
                    name = f"heartbeat {rec.get('verdict')}"
                elif kind == "launch":
                    name = f"launch attempt {rec.get('attempt')}"
                elif kind == "exchange":
                    name = f"exchange {rec.get('mode')}"
                elif kind == "policy_group":
                    name = f"policy_group {rec.get('group')}"
                elif kind == "migrate":
                    name = f"migrate@{rec.get('step')}"
                elif kind == "health":
                    name = (f"health {rec.get('group')} "
                            f"{rec.get('verdict')}" if rec.get("group")
                            else f"health {rec.get('verdict')}")
                elif kind == "anomaly":
                    name = f"anomaly {rec.get('anomaly')}"
                args = {k: v for k, v in rec.items()
                        if k not in ("schema", "kind", "t")
                        and isinstance(v, (str, int, float, bool))}
                # the scalars-only filter above would drop the list
                # payloads these events are ABOUT — summarize them
                if kind == "policy":
                    gds = rec.get("group_decisions")
                    if isinstance(gds, list) and gds:
                        args["groups"] = ",".join(
                            str(d.get("group")) for d in gds
                            if isinstance(d, dict))
                elif kind == "policy_group":
                    modes = rec.get("modes")
                    if isinstance(modes, (list, tuple)):
                        args["modes"] = ",".join(str(m) for m in modes)
                    elif isinstance(modes, dict):
                        args["modes"] = ",".join(
                            f"{k}={v}" for k, v in sorted(modes.items()))
                elif kind == "anomaly":
                    suspect = rec.get("suspect")
                    if isinstance(suspect, dict):
                        args["suspect"] = (f"{suspect.get('kind')}:"
                                           f"{suspect.get('name')}")
                args["file"] = src
                events.append({"name": name, "ph": "i", "s": "t",
                               "cat": kind, "ts": _us(t), "pid": pid,
                               "tid": tid_num, "args": args})
    spans = sum(1 for e in events if e.get("cat") == "span")
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "obs_trace_export",
            "files": files_read,
            "processes": len(pids),
            "spans": spans,
            "trace_ids": sorted(trace_ids),
        },
    }


def validate_export(obj: Any) -> List[str]:
    """Schema gate on the built trace: list EVERY problem, empty = ok."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return [f"export must be a dict, got {type(obj).__name__}"]
    evs = obj.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents must be a list"]
    for i, e in enumerate(evs):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            problems.append(f"{where}: not a dict")
            continue
        ph = e.get("ph")
        if ph not in ("X", "i", "M"):
            problems.append(f"{where}: ph must be X/i/M (got {ph!r})")
            continue
        if not isinstance(e.get("name"), str) or not e["name"]:
            problems.append(f"{where}: name must be a nonempty str")
        if not isinstance(e.get("pid"), int) or \
                not isinstance(e.get("tid"), int):
            problems.append(f"{where}: pid/tid must be ints")
        if ph in ("X", "i"):
            if not isinstance(e.get("ts"), (int, float)):
                problems.append(f"{where}: ts must be a number")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur <= 0:
                problems.append(f"{where}: X needs dur > 0 (got {dur!r})")
        if ph == "M" and not isinstance(e.get("args"), dict):
            problems.append(f"{where}: M needs an args dict")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("paths", nargs="+",
                    help="telemetry JSONL file(s), a directory, or a "
                         "supervised run's base path (siblings "
                         "auto-discovered)")
    ap.add_argument("-o", "--out", default=None,
                    help="output JSON path (default: stdout)")
    a = ap.parse_args(argv)
    paths: List[str] = []
    for arg in a.paths:
        for p in discover(arg):
            if p not in paths:
                paths.append(p)
    if not paths:
        print(f"obs_trace_export: no logs found under {a.paths}",
              file=sys.stderr)
        return 2
    obj = build_trace(paths)
    problems = validate_export(obj)
    if problems:
        print("obs_trace_export: invalid export:\n  "
              + "\n  ".join(problems), file=sys.stderr)
        return 1
    body = json.dumps(obj, default=str)
    if a.out:
        with open(a.out, "w") as fh:
            fh.write(body)
        meta = obj["otherData"]
        print(f"obs_trace_export: {len(obj['traceEvents'])} events from "
              f"{meta['files']} log(s), {meta['processes']} process "
              f"track(s), {meta['spans']} spans, trace_ids="
              f"{meta['trace_ids']} -> {a.out}")
    else:
        print(body)
    return 0


if __name__ == "__main__":
    sys.exit(main())
