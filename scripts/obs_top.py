#!/usr/bin/env python
"""Terminal live monitor: one refreshing screen per run (or campaign).

The human face of the live-observability layer (``obs/serve.py``):
point it at

* a **URL** (``http://host:port`` from ``--serve``) — polls
  ``/status.json`` and renders the remote run live;
* a **telemetry JSONL path** — re-reads the log each refresh and
  renders the same view locally (works on a finished or in-flight log,
  no server needed);
* a **ledger JSONL path** (e.g. the committed
  ``benchmarks/ledger.jsonl``) — renders the campaign state:
  ``best_known`` per label x backend plus quarantine counts/reasons.

One screen: run header (what/where/provenance), a throughput sparkline
over the recent chunks, the predicted-vs-measured roofline line, the
heartbeat/restart status ("is it wedged?" at a glance), and — for
campaign logs — the per-label table with deltas against the ledger's
``best_known`` baselines.

``--once`` renders a single frame and exits (scripts/CI); the default
loop clears and redraws every ``--interval`` seconds until Ctrl-C.

Safe on a wedged box: CPU is forced before any jax-touching import and
nothing here contacts a device.

Usage:  python scripts/obs_top.py URL|PATH [--interval S] [--once]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from cpuforce import force_cpu  # noqa: E402

force_cpu()  # before the package (and hence any jax backend) loads

from mpi_cuda_process_tpu.obs import ledger as ledger_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import metrics as metrics_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import trace as trace_lib  # noqa: E402

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 48) -> str:
    """Unicode sparkline of the last ``width`` values (min-max scaled)."""
    vals = [float(v) for v in values if v is not None][-width:]
    if not vals:
        return "(no samples yet)"
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[3] * len(vals)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((v - lo) / span * (len(_SPARK) - 1) + 0.5))]
        for v in vals)


def _age(ts) -> str:
    if not isinstance(ts, (int, float)):
        return "-"
    s = max(0.0, time.time() - ts)
    if s < 90:
        return f"{s:.0f}s ago"
    if s < 5400:
        return f"{s / 60:.0f}m ago"
    return f"{s / 3600:.1f}h ago"


def _table(rows, header):
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    out = ["  ".join(str(h).ljust(w) for h, w in zip(header, widths))]
    out.append("  ".join("-" * w for w in widths))
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


# ------------------------------------------------------------ run frame

def _header_lines(status) -> list:
    m = status.get("manifest") or {}
    run = m.get("run") or {}
    prov = m.get("provenance") or {}
    grid = "x".join(map(str, run.get("grid") or [])) or "-"
    mesh = "x".join(map(str, run.get("mesh") or [])) or "-"
    host = prov.get("hostname") or "?"
    pidx = prov.get("process_index")
    pcnt = prov.get("process_count")
    proc = f"  p{pidx}/{pcnt}" if pidx is not None else ""
    lines = [
        f"run   tool={m.get('tool', '?')}  "
        f"stencil={run.get('stencil', '-')}  grid={grid}  mesh={mesh}  "
        f"backend={prov.get('backend', '?')} "
        f"({prov.get('device_count', '?')}x "
        f"{prov.get('device_kind', '?')})",
        f"      host={host}{proc}  "
        f"git={str(prov.get('git_sha', '?'))[:12]}  "
        f"jax={prov.get('jax_version', '?')}  "
        f"started {_age(m.get('created_at'))}  "
        f"events={status.get('events_seen', 0)}",
    ]
    flags = [k for k in ("overlap", "pipeline", "supervise") if run.get(k)]
    extra = []
    if run.get("ensemble"):
        em = run.get("ensemble_mesh")
        extra.append(f"ensemble={run['ensemble']}"
                     + (f"(x{em} mesh)" if em and em > 1 else ""))
    if run.get("fuse"):
        extra.append(f"fuse={run['fuse']}({run.get('fuse_kind', 'auto')})")
    if run.get("exchange") and run.get("exchange") != "ppermute":
        extra.append(f"exchange={run['exchange']}")
    if run.get("kernel_variant"):
        extra.append(f"variant={run['kernel_variant']}")
    if run.get("groups"):
        extra.append(f"groups={run['groups']}")
    extra += flags
    if extra:
        lines.append("      " + "  ".join(extra))
    return lines


def _throughput_lines(status) -> list:
    chunks = status.get("chunks_recent") or []
    rates = [c["steps"] / c["wall_s"] for c in chunks
             if c.get("wall_s") and c.get("steps")]
    tp = status.get("throughput") or {}
    bits = []
    if "steps_per_s" in tp:
        bits.append(f"{tp['steps_per_s']:g} steps/s")
    if "gcells_per_s" in tp:
        label = " Gcells/s (aggregate)" if tp.get("ensemble") else \
            " Gcells/s"
        bits.append(f"{tp['gcells_per_s']:g}{label}")
    if "gcells_per_s_per_member" in tp:
        bits.append(f"{tp['gcells_per_s_per_member']:g} Gcells/s/member "
                    f"x{tp['ensemble']}")
    if "steady_ms_per_step_p50" in tp:
        bits.append(f"steady p50 {tp['steady_ms_per_step_p50']:.4g} "
                    f"ms/step (p90 {tp.get('steady_ms_per_step_p90', 0):.4g})")
    lines = [f"rate  {sparkline(rates)}  "
             + ("  ".join(bits) if bits else "(no chunks yet)")]
    roof = status.get("roofline") or {}
    t_hbm = roof.get("predicted_ms_per_step_hbm")
    if t_hbm is not None:
        t_ici = roof.get("predicted_ms_per_step_exchange") or 0.0
        pred = max(t_hbm, t_ici)
        line = (f"roof  predicted {pred:.4g} (overlapped) / "
                f"{t_hbm + t_ici:.4g} (serial) ms/step")
        measured = tp.get("steady_ms_per_step_p50")
        if measured is not None and pred > 0:
            line += (f" — measured p50 {measured:.4g} "
                     f"(gap {measured / pred:.2f}x)")
        lines.append(line)
    return lines


def _sim_health_lines(status) -> list:
    """Numerics-sentinel line (obs/health.py): verdict, invariant
    drift, NaN counts, worst-field drift, halo-audit state."""
    h = status.get("health")
    audit = status.get("halo_audit")
    if not h and not audit:
        return []
    lines = []
    if h:
        bits = [f"verdict={h.get('verdict', '?')}"]
        inv = h.get("invariant") or {}
        if inv.get("name"):
            d = inv.get("drift")
            if isinstance(d, list):
                d = max((x for x in d if isinstance(x, (int, float))),
                        default=None)
            bits.append(f"{inv['name']}={_fmtv(inv.get('value'))}"
                        + (f" (drift {d:.3g}, tol {inv.get('rtol')})"
                           if isinstance(d, (int, float)) else ""))
        if h.get("nonfinite_total"):
            bits.append(f"nonfinite={h['nonfinite_total']}")
        wf = h.get("worst_field") or {}
        if isinstance(wf.get("drift"), (int, float)):
            bits.append(f"worst-field f{wf.get('field')} "
                        f"drift {wf['drift']:.3g}")
        ens = h.get("ensemble") or {}
        if ens.get("members"):
            bits.append(f"members={ens['members']}"
                        + (f" spread={ens.get('spread'):.3g}"
                           if isinstance(ens.get("spread"),
                                         (int, float)) else ""))
        lines.append("sim     " + "  ".join(bits))
        if h.get("reason"):
            lines.append(f"        {str(h['reason'])[:100]}")
    if audit:
        ok = "ok" if audit.get("ok") else "MISMATCH"
        line = (f"halo    audit={ok}  sites={audit.get('sites_checked')}"
                f"  backend={audit.get('backend')}")
        if not audit.get("ok"):
            bad = [s for s in (audit.get("sites") or [])
                   if s.get("mismatch_count")]
            for s in bad[:3]:
                line += (f"\n        field {s.get('field')} axis "
                         f"{s.get('axis')} {s.get('direction')} "
                         f"shards {s.get('mismatch_shards')} "
                         f"({s.get('mismatch_count')} words)")
        lines.append(line)
    return lines


def _fmtv(v):
    if isinstance(v, list):
        return "[" + ",".join(f"{x:.4g}" if isinstance(x, (int, float))
                              else str(x) for x in v[:4]) + \
            ("…]" if len(v) > 4 else "]")
    if isinstance(v, (int, float)):
        return f"{v:.6g}"
    return str(v)


def _groups_lines(status) -> list:
    """Coupled-run panel (parallel/groups.py): one row per device
    group — op, resolution, dtype, devices, execution mode, throughput,
    verdict — already ranked worst verdict first by the metrics
    aggregator.  The header names the interface transport (round 23):
    a collective run's bands ride ICI ppermute rounds, a device_put
    run's ride host-mediated transfers."""
    groups = status.get("groups")
    if not groups:
        return []
    worst = groups.get("worst_verdict")
    transport = next((r.get("transport")
                      for r in groups.get("rows") or ()
                      if r.get("transport")), None)
    head = (f"groups  {groups.get('n_groups', '?')} device groups "
            f"coupled at interface faces"
            + (f"  transport={transport}" if transport else "")
            + (f"  worst={worst}" if worst else ""))
    rows = []
    for r in groups.get("rows") or ():
        ratio = r.get("ratio")
        res = (f"fine x{ratio}" if isinstance(ratio, int) and ratio > 1
               else "base")
        mc = r.get("mcells_per_s")
        gc = f"{mc / 1000:.4g}" if isinstance(mc, (int, float)) else "-"
        devs = r.get("devices")
        dev = ("-".join(map(str, devs)) if isinstance(devs, (list, tuple))
               and len(devs) == 2 else "-")
        modes = r.get("modes")
        mode = ("+".join(modes) if isinstance(modes, (list, tuple))
                and modes else "plain")
        rows.append([
            r.get("group", "?"), r.get("op", "-"), res,
            r.get("dtype", "-"), dev, mode, gc, r.get("verdict") or "-"])
    return [head, _table(rows, ["group", "op", "resolution", "dtype",
                                "devices", "mode", "Gcells/s",
                                "verdict"])]


def _health_lines(status) -> list:
    hb = status.get("heartbeat") or {}
    chunk = status.get("latest_chunk") or {}
    bits = [f"verdict={status.get('verdict', '?')}"]
    if chunk:
        bits.append(f"chunk {chunk.get('chunk')} "
                    f"({_age(chunk.get('t'))})")
    restarts = status.get("restarts") or []
    if status.get("launches"):
        bits.append(f"attempts={len(status['launches'])}")
    if restarts:
        bits.append(f"restarts={len(restarts)}")
    if status.get("resumed_from_step") is not None:
        bits.append(f"resumed_from_step={status['resumed_from_step']}")
    if status.get("give_up"):
        bits.append("GAVE UP")
    lines = ["health  " + "  ".join(bits)]
    if hb.get("detail") and hb.get("verdict") not in (None, "RECOVERED"):
        lines.append(f"        {str(hb['detail'])[:100]}")
    for r in restarts[-3:]:
        lines.append(f"        restart: {r.get('reason', '?')} "
                     f"(backoff {r.get('backoff_s', '?')}s, "
                     f"checkpoint {r.get('checkpoint_step')})")
    summary = status.get("summary")
    if summary:
        bits = [f"{k}={summary[k]}" for k in
                ("ok", "steps", "mcells_per_s", "converged", "labels_run")
                if k in summary]
        lines.append("done    " + ("  ".join(bits) if bits else "summary"))
    for e in (status.get("errors") or [])[-2:]:
        lines.append(f"ERROR   {str(e.get('error') or e.get('reason'))[:100]}")
    return lines


def _campaign_lines(status, ledger_path) -> list:
    camp = status.get("campaign")
    if not camp:
        return []
    best = {}
    try:
        best = ledger_lib.best_known(ledger_lib.read_rows(ledger_path))
    except Exception:  # noqa: BLE001 — the monitor renders anyway
        pass
    backend = ((status.get("manifest") or {}).get("provenance")
               or {}).get("backend")
    counts = "  ".join(f"{k}={v}"
                       for k, v in sorted(camp["counts"].items()))
    rows = []
    for label, rec in camp["labels"].items():
        bk = best.get(f"{label}|{backend}")
        base = bk["value"] if bk else None
        val = rec.get("mcells_per_s")
        if isinstance(val, (int, float)) and isinstance(base, (int, float)) \
                and base > 0:
            delta = f"{(val / base - 1) * 100:+.1f}%"
        else:
            delta = "-"
        rows.append([
            label, rec.get("status") or "-",
            val if val is not None else "-",
            base if base is not None else "-", delta,
            (str(rec.get("error") or "")[:36])])
    return [f"campaign ({len(rows)} labels: {counts})",
            _table(rows, ["label", "status", "Mcells/s",
                          "best_known", "delta", "error"])]


def _scheduler_lines(status) -> list:
    """Serving-scheduler panel (serving/scheduler.py event stream):
    occupancy gauges, decision counts, per-tenant ops, last reject."""
    sched = status.get("scheduler")
    if not sched:
        return []
    bits = []
    for k in ("queue_depth", "slots_busy", "slots_total", "classes"):
        if sched.get(k) is not None:
            bits.append(f"{k}={sched[k]}")
    counts = sched.get("counts") or {}
    for op in ("submit", "retire", "reject", "evict", "preempt",
               "cancel", "grow", "shrink"):
        if counts.get(op):
            bits.append(f"{op}={counts[op]}")
    lines = ["sched   " + "  ".join(bits)]
    last = sched.get("last_event") or {}
    if last:
        lines.append(f"        last: {last.get('op', '?')} "
                     f"tenant={last.get('tenant') or '-'} "
                     f"job={last.get('job') or '-'} "
                     f"class={last.get('size_class') or '-'} "
                     f"({_age(last.get('t'))})")
    rej = sched.get("last_reject")
    if rej:
        lines.append(f"        reject: tenant={rej.get('tenant') or '-'} "
                     f"reason={rej.get('reason') or '?'} "
                     f"class={rej.get('size_class') or '-'}")
    tenants = sched.get("tenants") or {}
    if tenants:
        rows = []
        for name in sorted(tenants):
            t = tenants[name]
            rows.append([name] + [t.get(op, 0) for op in
                                  ("submit", "join", "retire", "evict",
                                   "preempt", "cancel", "reject")])
        lines.append(_table(rows, ["tenant", "submit", "join", "retire",
                                   "evict", "preempt", "cancel",
                                   "reject"]))
    return lines


def _fleet_lines(status) -> list:
    """Fleet panel (serving/router.py behind the obs/aggregate.py
    roll-up): router decision counters + one row per engine replica —
    verdict, queue depth, slot occupancy, grow/shrink counts, and the
    per-class capacity table."""
    replicas = [r for r in (status.get("hosts") or ())
                if r.get("replica")]
    router = status.get("router")
    if not router and not replicas:
        return []
    lines = []
    if router:
        counts = router.get("counts") or {}
        bits = [f"replicas={router.get('replicas_alive', '?')}/"
                f"{router.get('replicas_total', '?')}",
                f"inflight={router.get('jobs_inflight', 0)}"]
        for op in ("route", "rebalance", "reject", "replica_dead",
                   "give_up"):
            if counts.get(op):
                bits.append(f"{op}={counts[op]}")
        lines.append("router  " + "  ".join(bits))
        death = router.get("last_death")
        if death:
            lines.append(f"        last death: "
                         f"{death.get('replica') or '?'} "
                         f"orphans={death.get('orphans', 0)} "
                         f"({_age(death.get('t'))})")
    if replicas:
        trows = []
        for r in sorted(replicas, key=lambda r: str(r.get("replica"))):
            sched = r.get("scheduler") or {}
            counts = sched.get("counts") or {}
            classes = sched.get("size_classes") or {}
            cls_bits = []
            for sc in sorted(classes):
                c = classes[sc]
                tag = str(sc)[:14]
                if c.get("capacity") is not None:
                    tag += (f" {c.get('occupied', '?')}"
                            f"/{c['capacity']}")
                cls_bits.append(tag)
            trows.append([
                r.get("replica"),
                r.get("verdict") or "-",
                sched.get("queue_depth", "-"),
                f"{sched.get('slots_busy', '-')}"
                f"/{sched.get('slots_total', '-')}",
                counts.get("grow", 0), counts.get("shrink", 0),
                "  ".join(cls_bits) or "-"])
        lines.append(_table(trows, ["replica", "verdict", "queue",
                                    "slots", "grow", "shrink",
                                    "classes occ/cap"]))
    return lines


def _policy_lines(status) -> list:
    """Elastic-engine panel (policy/select.py + reshard adoption): the
    active decision, its provenance, any overrides, migration count."""
    pol = status.get("policy")
    if not pol:
        return []
    decision = pol.get("decision") or {}
    mode_bits = []
    for k in ("mesh", "ensemble_mesh", "fuse", "fuse_kind", "overlap",
              "pipeline", "exchange", "kernel_variant"):
        v = decision.get(k)
        if v in (None, 0, False, [], "auto", "ppermute", ""):
            continue
        mode_bits.append(f"{k}={'x'.join(map(str, v)) if isinstance(v, list) else v}")
    val = pol.get("value")
    bits = [pol.get("provenance") or "?",
            pol.get("label") or "?",
            " ".join(mode_bits) if mode_bits else "(plain)"]
    if val is not None:
        bits.append(f"{val} {pol.get('unit') or 'Mcells/s'}")
    lines = ["policy  " + "  ".join(bits)]
    overrides = pol.get("overrides") or {}
    if overrides:
        lines.append("        overrides: "
                     + " ".join(f"{k}={v}" for k, v in
                                sorted(overrides.items())))
    n_mig = pol.get("migrations") or 0
    last = pol.get("last_migration")
    if n_mig and last:
        dst = last.get("dst") or {}
        mesh = dst.get("mesh")
        lines.append(f"        migrations: {n_mig}  last: step "
                     f"{last.get('step', '?')} -> "
                     f"{last.get('label') or '?'} "
                     f"mesh={'x'.join(map(str, mesh)) if mesh else '-'} "
                     f"({last.get('rounds', '?')} comm rounds)")
    return lines


def _anomaly_lines(status) -> list:
    """Run-doctor panel (obs/anomaly.py): finding counts by kind plus
    the latest finding and its suspect — the evidence behind a
    DEGRADED verdict, rendered only when findings exist (a clean run
    shows nothing)."""
    an = status.get("anomalies")
    if not an:
        return []
    kinds = " ".join(f"{k}={v}"
                     for k, v in sorted((an.get("kinds") or {}).items()))
    lines = [f"doctor  {an.get('count', 0)} anomaly finding(s)  {kinds}"]
    last = an.get("last") or {}
    suspect = an.get("suspect") or last.get("suspect") or {}
    if last:
        bits = [f"last: {last.get('anomaly', '?')}",
                f"sev={last.get('severity', '?')}"]
        if last.get("chunk") is not None:
            bits.append(f"chunk={last.get('chunk')}")
        if suspect:
            tag = (f"suspect={suspect.get('kind', '?')}:"
                   f"{suspect.get('name', '?')}")
            lag = suspect.get("lag_ratio")
            if lag:
                tag += f" (x{lag})"
            bits.append(tag)
        lines.append("        " + "  ".join(bits))
    return lines


def _hosts_lines(status) -> list:
    """Per-host/process table (obs/aggregate.py roll-up, when served)."""
    hosts = status.get("hosts")
    if not hosts:
        return []
    agg = status.get("aggregate") or {}
    rows = []
    for r in hosts:
        tp = r.get("throughput") or {}
        chunk = r.get("latest_chunk") or {}
        rows.append([
            f"{r.get('hostname', '?')} p{r.get('process_index', '?')}",
            r.get("verdict") or "-",
            chunk.get("chunk") if chunk else "-",
            tp.get("gcells_per_s", "-"),
            r.get("restarts") or 0,
            r.get("time_to_first_chunk_s", "-"),
            str(r.get("trace_id") or "-")[:12]])
    head = (f"hosts ({agg.get('processes', len(rows))} processes on "
            f"{agg.get('hosts', '?')} host(s): "
            f"verdict={agg.get('verdict', '?')}  "
            f"{agg.get('gcells_per_s', 0)} Gcells/s aggregate)")
    return [head, _table(rows, ["host", "verdict", "chunk", "Gcells/s",
                                "restarts", "ttfc_s", "trace"])]


def run_frame(status, ledger_path) -> str:
    lines = _header_lines(status)
    lines += _throughput_lines(status)
    lines += _health_lines(status)
    lines += _sim_health_lines(status)
    lines += _anomaly_lines(status)
    lines += _groups_lines(status)
    lines += _scheduler_lines(status)
    lines += _fleet_lines(status)
    lines += _policy_lines(status)
    lines += _hosts_lines(status)
    lines += _campaign_lines(status, ledger_path)
    return "\n".join(lines)


# --------------------------------------------------------- ledger frame

def ledger_frame(path) -> str:
    rows = ledger_lib.read_rows(path)
    best = ledger_lib.best_known(rows)
    quarantined = [r for r in rows if r.get("status") == "quarantined"]
    reasons = {}
    for r in quarantined:
        key = str(r.get("quarantine") or "?").split(":")[0]
        reasons[key] = reasons.get(key, 0) + 1
    out = [f"ledger {path}: {len(rows)} rows "
           f"({len(quarantined)} quarantined), {len(best)} baselines"]
    # staleness flag: the distinct UTC days best_known rows were
    # measured on stand in for campaign rounds; a baseline older than
    # the latest two measurement days is a number nobody has
    # re-confirmed recently — flagged, never hidden
    def _day(ts):
        return (time.strftime("%Y-%m-%d", time.gmtime(ts))
                if isinstance(ts, (int, float)) else None)
    days = sorted({d for d in (_day(best[bk].get("measured_at"))
                               for bk in best) if d}, reverse=True)
    fresh = set(days[:2])
    trows = []
    for bk in sorted(best):
        r = best[bk]
        ts = r.get("measured_at")
        age_d = (f"{max(0.0, time.time() - ts) / 86400:.1f}"
                 if isinstance(ts, (int, float)) else "-")
        flag = "" if _day(ts) in fresh else "stale?"
        trows.append([bk, r["value"], r["unit"],
                      _age(ts), age_d, flag, r["source"][:40]])
    if trows:
        out.append(_table(trows, ["label|backend", "best", "unit",
                                  "measured", "age_d", "flag", "source"]))
    if reasons:
        out.append("quarantine reasons:")
        for k, v in sorted(reasons.items(), key=lambda kv: -kv[1]):
            out.append(f"  {v:4d}  {k}")
    return "\n".join(out)


# -------------------------------------------------------------- sources

def _status_from_url(url: str):
    base = url.rstrip("/")
    with urllib.request.urlopen(base + "/status.json", timeout=10) as r:
        return json.load(r)


def _status_from_log(path: str):
    manifest, events = trace_lib.read_log(path)
    rm = metrics_lib.RunMetrics()
    rm.ingest(manifest)
    for e in events:
        rm.ingest(e)
    return rm.status()


def _is_ledger(path: str) -> bool:
    try:
        with open(path) as fh:
            first = fh.readline().strip()
        return bool(first) and \
            json.loads(first).get("kind") == "ledger_row"
    except (OSError, ValueError):
        return False


def frame(source: str, ledger_path: str):
    """One rendered frame: ``(text, status-or-None)`` — the status dict
    rides along so ``--once`` can turn health into an exit code
    (ledger frames have no run health; status is None)."""
    if source.startswith(("http://", "https://")):
        status = _status_from_url(source)
        return run_frame(status, ledger_path), status
    if _is_ledger(source):
        return ledger_frame(source), None
    status = _status_from_log(source)
    return run_frame(status, ledger_path), status


def health_rc(status) -> int:
    """CI/campaign health probe verdict for ``--once``: nonzero when
    the latest heartbeat verdict is WEDGED/STALLED, the numerics
    sentinel says DIVERGED (same contract — a diverged run failed, in
    the way that matters most), the run doctor says DEGRADED (it
    finished, but slower than its own evidence says it should have —
    a CI gate must notice), the supervisor gave up, or — on an
    aggregate page — ANY host is in one of those states."""
    if not status:
        return 0
    bad = ("WEDGED", "STALLED", "GAVE_UP", "DIVERGED", "DEGRADED")
    if status.get("verdict") in bad or status.get("give_up"):
        return 1
    if (status.get("health") or {}).get("verdict") == "DIVERGED":
        return 1
    agg = status.get("aggregate") or {}
    if agg.get("verdict") in bad:
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("source",
                    help="http://host:port (a --serve console), a "
                         "telemetry JSONL path, or a ledger JSONL path")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (no clear, no "
                         "loop); the exit code is a health probe — "
                         "nonzero on a WEDGED/STALLED verdict or a "
                         "supervisor give-up, so CI and campaign "
                         "scripts can gate on it")
    ap.add_argument("--ledger", default=None,
                    help="ledger path for campaign best_known deltas "
                         f"(default {ledger_lib.default_ledger_path()})")
    a = ap.parse_args(argv)
    ledger_path = a.ledger or ledger_lib.default_ledger_path()
    if a.once:
        body, status = frame(a.source, ledger_path)
        print(body)
        return health_rc(status)
    try:
        while True:
            body, _status = frame(a.source, ledger_path)
            sys.stdout.write("\x1b[2J\x1b[H" + body + "\n")
            sys.stdout.flush()
            time.sleep(a.interval)
    except KeyboardInterrupt:
        return 0
    except OSError as e:
        # the server going away is how a watched run ENDS, not a crash
        print(f"\nobs_top: source gone ({e}) — run over?",
              file=sys.stderr)
        return 0


if __name__ == "__main__":
    sys.exit(main())
