// Native host-side runtime for the TPU stencil framework.
//
// The reference's host layer is native C++ (buffer management kernel.cu:184-191,
// init kernel.cu:131-146, renderer kernel.cu:115-129); this library is its
// TPU-framework counterpart, providing:
//
//   1. An async .npy writer: a background thread pool that serializes field
//      snapshots to disk (atomic tmp+rename per file) without blocking the
//      host step loop — the role the reference's host double buffer was
//      meant to play for device results (SURVEY.md C14), done properly.
//   2. Independent golden stencil engines (Game of Life per kernel.cu:10-68's
//      B3/S23 rule; 7-point FTCS per MDF_kernel.cu:20) used by the test suite
//      as a second, non-JAX implementation for differential testing.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// minimal .npy v1.0 writer (C order, little endian)
// ---------------------------------------------------------------------------

std::string npy_header(const char* descr, const int64_t* shape, int ndim) {
  std::string dict = "{'descr': '";
  dict += descr;
  dict += "', 'fortran_order': False, 'shape': (";
  for (int i = 0; i < ndim; ++i) {
    dict += std::to_string(shape[i]);
    if (ndim == 1 || i + 1 < ndim) dict += ", ";
  }
  dict += "), }";
  // pad with spaces so that 10 + len(header) is a multiple of 64
  size_t unpadded = 10 + dict.size() + 1;  // +1 for trailing newline
  size_t padded = (unpadded + 63) / 64 * 64;
  dict.append(padded - unpadded, ' ');
  dict += '\n';

  std::string out;
  out += "\x93NUMPY";
  out += '\x01';
  out += '\x00';
  uint16_t hlen = static_cast<uint16_t>(dict.size());
  out += static_cast<char>(hlen & 0xff);
  out += static_cast<char>(hlen >> 8);
  out += dict;
  return out;
}

bool write_npy_file(const std::string& path, const char* descr,
                    const void* data, const int64_t* shape, int ndim,
                    int64_t itemsize) {
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  std::string tmp = path + ".tmp";
  FILE* f = std::fopen(tmp.c_str(), "wb");
  if (!f) return false;
  std::string hdr = npy_header(descr, shape, ndim);
  bool ok = std::fwrite(hdr.data(), 1, hdr.size(), f) == hdr.size();
  ok = ok && std::fwrite(data, static_cast<size_t>(itemsize),
                         static_cast<size_t>(n), f) ==
                 static_cast<size_t>(n);
  ok = std::fflush(f) == 0 && ok;
  ok = std::fclose(f) == 0 && ok;
  if (ok) ok = std::rename(tmp.c_str(), path.c_str()) == 0;
  if (!ok) std::remove(tmp.c_str());
  return ok;
}

// ---------------------------------------------------------------------------
// background writer pool
// ---------------------------------------------------------------------------

class WriterPool {
 public:
  explicit WriterPool(int n_threads) : stop_(false), pending_(0), errors_(0) {
    for (int i = 0; i < n_threads; ++i)
      workers_.emplace_back([this] { this->worker(); });
  }

  ~WriterPool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  void submit(std::string path, std::string descr, std::vector<char> data,
              std::vector<int64_t> shape, int64_t itemsize) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      ++pending_;
      jobs_.emplace_back([this, path = std::move(path),
                          descr = std::move(descr), data = std::move(data),
                          shape = std::move(shape), itemsize]() {
        if (!write_npy_file(path, descr.c_str(), data.data(), shape.data(),
                            static_cast<int>(shape.size()), itemsize))
          ++errors_;
      });
    }
    cv_.notify_one();
  }

  // Block until all submitted jobs completed; returns error count since start.
  int64_t wait_all() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this] { return pending_ == 0; });
    return errors_.load();
  }

  int64_t pending() {
    std::unique_lock<std::mutex> lk(mu_);
    return pending_;
  }

 private:
  void worker() {
    for (;;) {
      std::function<void()> job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !jobs_.empty(); });
        if (stop_ && jobs_.empty()) return;
        job = std::move(jobs_.front());
        jobs_.pop_front();
      }
      job();
      {
        std::unique_lock<std::mutex> lk(mu_);
        if (--pending_ == 0) done_cv_.notify_all();
      }
    }
  }

  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
  std::deque<std::function<void()>> jobs_;
  std::vector<std::thread> workers_;
  bool stop_;
  int64_t pending_;
  std::atomic<int64_t> errors_;
};

WriterPool* pool() {
  static WriterPool p(2);
  return &p;
}

}  // namespace

extern "C" {

// Queue an async .npy write; the data is copied before returning, so the
// caller's buffer may be reused immediately.
int stencilhost_async_write_npy(const char* path, const char* descr,
                                const void* data, const int64_t* shape,
                                int ndim, int64_t itemsize) {
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  std::vector<char> copy(static_cast<size_t>(n * itemsize));
  std::memcpy(copy.data(), data, copy.size());
  pool()->submit(path, descr, std::move(copy),
                 std::vector<int64_t>(shape, shape + ndim), itemsize);
  return 0;
}

// Wait for all queued writes; returns the cumulative error count.
int64_t stencilhost_wait_all(void) { return pool()->wait_all(); }

int64_t stencilhost_pending(void) { return pool()->pending(); }

// Synchronous write (same format), for the fallback path and tests.
int stencilhost_write_npy(const char* path, const char* descr,
                          const void* data, const int64_t* shape, int ndim,
                          int64_t itemsize) {
  return write_npy_file(path, descr, data, shape, ndim, itemsize) ? 0 : 1;
}

// ---------------------------------------------------------------------------
// golden stencil engines (independent of JAX, for differential tests)
// ---------------------------------------------------------------------------

// One B3/S23 Game-of-Life step on an h x w int32 grid; the 1-cell frame is
// treated as fixed (never rewritten), matching the framework's guard-frame
// semantics (and kernel.cu:66's rule).
void stencilhost_life_step(const int32_t* in, int32_t* out, int64_t h,
                           int64_t w) {
  std::memcpy(out, in, sizeof(int32_t) * static_cast<size_t>(h * w));
  for (int64_t y = 1; y + 1 < h; ++y) {
    for (int64_t x = 1; x + 1 < w; ++x) {
      int n = 0;
      for (int dy = -1; dy <= 1; ++dy)
        for (int dx = -1; dx <= 1; ++dx)
          if (dy || dx) n += in[(y + dy) * w + (x + dx)];
      int32_t alive = in[y * w + x];
      out[y * w + x] = (n == 3 || (n == 2 && alive == 1)) ? 1 : 0;
    }
  }
}

// One 7-point FTCS diffusion step on a d x h x w float32 grid, frame fixed.
void stencilhost_heat3d_step(const float* in, float* out, int64_t d, int64_t h,
                             int64_t w, float alpha) {
  std::memcpy(out, in, sizeof(float) * static_cast<size_t>(d * h * w));
  for (int64_t z = 1; z + 1 < d; ++z) {
    for (int64_t y = 1; y + 1 < h; ++y) {
      for (int64_t x = 1; x + 1 < w; ++x) {
        int64_t i = (z * h + y) * w + x;
        float u = in[i];
        float lap = in[i - 1] + in[i + 1] + in[i - w] + in[i + w] +
                    in[i - h * w] + in[i + h * w] - 6.0f * u;
        out[i] = u + alpha * lap;
      }
    }
  }
}

// One 5-point FTCS diffusion step on an h x w float32 grid, frame fixed
// (the reference MDF workload, MDF_kernel.cu:20's formula class).
void stencilhost_heat2d_step(const float* in, float* out, int64_t h, int64_t w,
                             float alpha) {
  std::memcpy(out, in, sizeof(float) * static_cast<size_t>(h * w));
  for (int64_t y = 1; y + 1 < h; ++y) {
    for (int64_t x = 1; x + 1 < w; ++x) {
      int64_t i = y * w + x;
      float u = in[i];
      float lap = in[i - 1] + in[i + 1] + in[i - w] + in[i + w] - 4.0f * u;
      out[i] = u + alpha * lap;
    }
  }
}

// One first-order upwind advection step (2D), frame fixed.  cy/cx are the
// signed Courant numbers for grid axes 0/1.
void stencilhost_advect2d_step(const float* in, float* out, int64_t h,
                               int64_t w, float cy, float cx) {
  std::memcpy(out, in, sizeof(float) * static_cast<size_t>(h * w));
  for (int64_t y = 1; y + 1 < h; ++y) {
    for (int64_t x = 1; x + 1 < w; ++x) {
      int64_t i = y * w + x;
      float u = in[i];
      float acc = u;
      if (cy > 0)
        acc -= cy * (u - in[i - w]);
      else if (cy < 0)
        acc -= cy * (in[i + w] - u);
      if (cx > 0)
        acc -= cx * (u - in[i - 1]);
      else if (cx < 0)
        acc -= cx * (in[i + 1] - u);
      out[i] = acc;
    }
  }
}

// One leapfrog FDTD wave step (2D): u_new = 2u - u_prev + c2dt2*Lap(u),
// frame keeps the old u (Dirichlet by induction — ops/wave.py); the caller
// carries the old u as the next u_prev, exactly like the scan carry.
void stencilhost_wave2d_step(const float* u, const float* uprev, float* out,
                             int64_t h, int64_t w, float c2dt2) {
  std::memcpy(out, u, sizeof(float) * static_cast<size_t>(h * w));
  for (int64_t y = 1; y + 1 < h; ++y) {
    for (int64_t x = 1; x + 1 < w; ++x) {
      int64_t i = y * w + x;
      float lap = u[i - 1] + u[i + 1] + u[i - w] + u[i + w] - 4.0f * u[i];
      out[i] = 2.0f * u[i] - uprev[i] + c2dt2 * lap;
    }
  }
}

// One Gray-Scott reaction-diffusion step (2D, both fields halo'd):
// u' = u + Du*Lap(u) - u v^2 + F (1-u); v' = v + Dv*Lap(v) + u v^2 -
// (F+kappa) v (ops/reaction.py), frames fixed.
void stencilhost_grayscott2d_step(const float* u, const float* v,
                                  float* out_u, float* out_v, int64_t h,
                                  int64_t w, float du, float dv, float f,
                                  float kappa) {
  std::memcpy(out_u, u, sizeof(float) * static_cast<size_t>(h * w));
  std::memcpy(out_v, v, sizeof(float) * static_cast<size_t>(h * w));
  for (int64_t y = 1; y + 1 < h; ++y) {
    for (int64_t x = 1; x + 1 < w; ++x) {
      int64_t i = y * w + x;
      float lap_u = u[i - 1] + u[i + 1] + u[i - w] + u[i + w] - 4.0f * u[i];
      float lap_v = v[i - 1] + v[i + 1] + v[i - w] + v[i + w] - 4.0f * v[i];
      float uvv = u[i] * v[i] * v[i];
      out_u[i] = u[i] + du * lap_u - uvv + f * (1.0f - u[i]);
      out_v[i] = v[i] + dv * lap_v + uvv - (f + kappa) * v[i];
    }
  }
}

// One 27-point high-order diffusion step (3D), frame fixed.  Weights by
// neighbor class (face 14/30, edge 3/30, corner 1/30, center -128/30 —
// ops/heat.py::heat3d27's discrete operator).
void stencilhost_heat3d27_step(const float* in, float* out, int64_t d,
                               int64_t h, int64_t w, float alpha) {
  const float wface = 14.0f / 30.0f, wedge = 3.0f / 30.0f,
              wcorner = 1.0f / 30.0f, wcenter = -128.0f / 30.0f;
  std::memcpy(out, in, sizeof(float) * static_cast<size_t>(d * h * w));
  for (int64_t z = 1; z + 1 < d; ++z) {
    for (int64_t y = 1; y + 1 < h; ++y) {
      for (int64_t x = 1; x + 1 < w; ++x) {
        int64_t i = (z * h + y) * w + x;
        float acc = wcenter * in[i];
        for (int dz = -1; dz <= 1; ++dz) {
          for (int dy = -1; dy <= 1; ++dy) {
            for (int dx = -1; dx <= 1; ++dx) {
              int nz = (dz != 0) + (dy != 0) + (dx != 0);
              if (nz == 0) continue;
              float wgt = nz == 1 ? wface : (nz == 2 ? wedge : wcorner);
              acc += wgt * in[i + (dz * h + dy) * w + dx];
            }
          }
        }
        out[i] = in[i] + alpha * acc;
      }
    }
  }
}

// One red-black SOR step (2D Laplace): red half-sweep (even coordinate
// parity) then black, the black sweep reading fresh red values; frame fixed.
void stencilhost_sor2d_step(const float* in, float* out, int64_t h, int64_t w,
                            float omega) {
  std::memcpy(out, in, sizeof(float) * static_cast<size_t>(h * w));
  for (int color = 0; color < 2; ++color) {
    for (int64_t y = 1; y + 1 < h; ++y) {
      for (int64_t x = 1; x + 1 < w; ++x) {
        if (((y + x) & 1) != color) continue;
        int64_t i = y * w + x;
        float nsum = out[i - 1] + out[i + 1] + out[i - w] + out[i + w];
        out[i] = (1.0f - omega) * out[i] + omega * 0.25f * nsum;
      }
    }
  }
}

}  // extern "C"
